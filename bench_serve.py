"""Serving benchmark: continuous-batching engine throughput under a Poisson
request stream (ref vLLM benchmark_serving; Orca iteration-level scheduling;
Sarathi chunked prefill; vLLM prefix caching).

Prints ONE JSON line: {"metric", "value", "unit", "requests", "decode_iters",
"decode_executables", "prefill_executables", "ttft_p50_ms", "ttft_p99_ms",
"prefix_hit_rate", ...}.

TPU: GPT-3 1.3B shape at bf16, 32-slot engine, 64 mixed-length requests drawn
from a Poisson arrival process.  CPU smoke (CI tier-1): `gpt_tiny`, 32
requests, <10 s — same scheduler/paging code paths, asserting the compiled
executable bound (1 decode + bounded prefill programs) that makes continuous
batching viable on TPU in the first place.

`--shared-prefix-frac F` gives a fraction F of requests a common system-style
prompt prefix so the prefix cache has something to hit — the win shows up as
`prefilled_tokens` dropping while `prefix_hit_rate` rises.  `--prefill-chunk
N` switches to Sarathi chunked prefill (prefill executable count collapses to
1-2 regardless of prompt-length spread).

`--spec-len K` (default 4; `--no-spec` disables) turns on speculative
decoding: n-gram self-drafting + one fixed-shape K+1-token verify executable.
The win shows up as `accepted_per_step` (mean tokens emitted per drafted
verify — 1.0 means drafts never helped) and the decode tokens/s delta vs the
`--no-spec` pass that main() runs alongside for comparison; `spec_parity`
confirms the two passes emitted byte-identical tokens (greedy acceptance is
lossless whenever verify and decode logits agree at argmax — exact at
matching kernel numerics; a TPU bf16 near-tie can in principle diverge).
The decode and verify executables are compiled during warmup
(`LLMEngine.warm_decode`/`warm_spec`) so the timed section measures
steady-state serving.

The engine defaults to the fused ONE-dispatch step (decode + interleaved
chunk + verify in a single program, on-device sampling, double-buffered
scheduling); `--no-fuse` is the escape hatch back to the legacy three-program
step, and the default run replays the same stream unfused to report
`fused_speedup` and byte-exact `fuse_parity`.  The JSON carries
`dispatches_per_step` (decode-path program dispatches per dispatching step —
1.0 fused) and `host_sync_ms_per_step` (blocking d2h sync time) straight from
the step timeline, plus the static roofline's `predicted_step_ms` for the
decode-side program at this engine's shapes (`analysis/cost_model.py`:
analytic flops vs compulsory HBM bytes over nameplate device specs) next to
`measured_step_ms`, with `model_error` = measured/predicted — meaningful on
TPU where the dispatch is device-bound, sanity-bounded only on the CPU smoke.

`--oversubscribe F` (> 0) shrinks the page pool so the submitted token
footprint is F x its capacity and flips admission to optimistic: prompt
footprint reserved at admit, pages grown token-granularly, victims preempted
under pressure (`--preempt {recompute,swap}` is the A/B axis — longer-prompt
replay through the prefix cache vs host-side KV parking + h2d restore).  The
JSON adds preemptions/step, the swap-vs-recompute split, swap_ms,
`goodput_tokens_per_sec` (tokens in final outputs only — recompute replays
earn nothing) and, from the unpressured comparison pass main() runs
alongside, `goodput_ratio` + byte-exact `oversubscribe_parity`; page/swap
accounting is invariant-checked at drain.

`--multi-turn N` replays multi-turn chat sessions (each request re-submits
its whole conversation, N turns, `--session-return-frac F` of sessions
returning) — the KV-tier workload: with tiering on (default; `--no-kv-tier`
disables, `--spill-dir D` adds a disk level) a returning session's evicted
conversation KV restores with ONE h2d scatter instead of a full re-prefill.
The JSON carries `resume_hits`/`resume_restored_tokens`/`partial_page_hits`
and the returning-turn-only `returning_prefilled_tokens` + TTFT; main() runs
a `--no-kv-tier` pass on the same stream for `returning_prefilled_drop` and
byte-exact `kv_tier_parity`.

`--mp N` serves tensor-parallel over N chips: Megatron-sharded serving params
(qkv/fc1 column-, proj/fc2 row-split), page pool head-sharded, paged
attention per-chip on the local head slice.  Greedy outputs are
token-identical to single-chip, and `decode_tokens_per_sec_per_chip` divides
by N.  On CPU, simulate the chips:
`XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
    python bench_serve.py --mp 2` (set automatically when absent).

`--replicas N` (> 1) adds the dp ENGINE-FLEET passes: the same multi-turn
session shape replayed through `EngineFleet` under `--router {affinity,
round_robin,least_loaded}` — plus, always, the round-robin cache-blind
baseline and a single-engine parity oracle on the identical pre-drawn
stream.  The row gains fleet tokens/s, per-replica balance, the
affinity-vs-round-robin returning-turn prefix-hit-rate and TTFT A/B
(`affinity_prefix_hit_ratio` is floor-enforced >= 1.0 by check_bench),
byte-exact `fleet_parity`, and `fleet_shared_executables` (replicas adopt
the leader's compiled programs — replication adds zero executables).

Latency percentiles (TTFT/TPOT/queue-time/e2e, p50/p99 ms) come from the
ENGINE's lifecycle histograms (`stats()["latency"]`), not a bench-side list —
the same numbers a Prometheus scrape of `engine.metrics` would see — and the
full metrics snapshot rides in the JSON under "metrics".  `--trace-dir D`
wraps the timed section in `engine.trace(D, device=False)`: chrome-trace of
the engine's host phases + per-step timeline + metrics dump.  Host-side
only — a jax device capture over a whole bench run would dominate the timed
section; for a device timeline, wrap a short window in `engine.trace(dir)`
directly (device capture is its default).

Every run appends ONE schema-versioned row (mode axes + key perf metrics +
parity flags) to `BENCH_SERVE.jsonl` — the serving perf trajectory across
PRs, validated and CI-floor-enforced by `tools/check_bench.py` (`--ci` runs
a fresh smoke bench against `SERVE_PERF_FLOORS` from the analysis registry).
`--no-history` opts out.
"""
from __future__ import annotations

import contextlib
import json
import sys
import time
from statistics import median

import numpy as np


def run_serve_bench(config=None, *, num_requests=32, num_slots=4,
                    page_size=8, max_model_len=None, max_new_tokens=8,
                    request_rate=float("inf"), seed=0, params=None,
                    prefill_chunk=None, prefix_cache=True,
                    shared_prefix_frac=0.0, spec_len=0, mp=1, fuse=True,
                    oversubscribe=0.0, preempt="recompute",
                    weight_dtype=None, kv_dtype=None,
                    kv_tier=True, spill_dir=None,
                    multi_turn=1, session_return_frac=1.0,
                    trace_dir=None, request_tracing=True,
                    debug_bundle_dir="serve_debug"):
    """Replay a Poisson request stream through LLMEngine; returns the metrics
    dict (also the CI smoke entrypoint — tests assert on the executable
    counts, the prefix-cache hit rate and the speculative acceptance rate).
    request_rate=inf enqueues everything up front (offline batch throughput);
    a finite rate interleaves arrivals with engine steps.  shared_prefix_frac
    gives that fraction of requests one common prompt prefix (~half the max
    prompt length, not page-aligned so the copy-on-write path is exercised
    too).  spec_len > 0 enables n-gram speculative decoding; the returned
    `outputs_digest` hashes every request's generated tokens in request-id
    order, so spec-on and spec-off passes over the same stream can assert
    exact greedy parity.  mp > 1 serves tensor-parallel over the first mp
    devices (head-sharded paged attention + Megatron serving params);
    tokens/s-per-chip then divides by the mesh size — the honest multi-chip
    number.

    oversubscribe=F (> 0) stress-tests overload handling: the page pool is
    shrunk so the submitted token footprint is F x its capacity, admission
    flips to optimistic (prompt-footprint-only, token-granular growth) and
    pool pressure preempts victims — `preempt` picks KV swap-out vs
    recompute.  The JSON then carries preemptions/step, the swap-vs-
    recompute split and `goodput_tokens_per_sec` (tokens in FINAL outputs
    per second — replayed prefill work earns nothing), and the page/swap
    accounting is invariant-checked at drain.

    multi_turn=N (> 1) switches the stream to MULTI-TURN CHAT sessions —
    the dominant traffic shape the KV tier exists for: each of the
    `num_requests` sessions re-submits its whole conversation
    (previous prompt + generated reply + a fresh user chunk) as the next
    turn's prompt, up to N turns; `session_return_frac` is the fraction of
    sessions that return after turn 1.  Follow-up turns enqueue the moment
    the previous turn finishes, so concurrent sessions thrash the device
    prefix cache between a session's visits — exactly the eviction pattern
    that makes the tier matter.  kv_tier=True (default; `--no-kv-tier`
    disables) lets evicted session KV spill to the bounded host tier
    (+ optional `spill_dir` disk level) and restore by one scatter; the
    returned `resume_hits`/`resume_restored_tokens` and the
    returning-turn-only `returning_prefilled_tokens` /
    `returning_ttft_p50_ms` quantify the win, and main()'s `--no-kv-tier`
    comparison pass reports `returning_prefilled_drop` + byte-exact
    `kv_tier_parity` on the same stream.  In multi-turn mode the
    outputs digest orders streams by (session, turn) — request ids are
    assigned in finish order, which scheduling may permute between
    passes — so parity compares conversations, not id assignment.

    weight_dtype/kv_dtype ("int8" or None/"bf16") run the engine quantized
    (weight-only int8 params / int8 KV page pool).  Under oversubscribe an
    int8 KV pool is sized to the SAME HBM byte budget as the fp pool would
    get — smaller pages mean proportionally more of them, which is exactly
    the capacity claim under test: the quantized pass should preempt less
    at the same byte pressure.  The returned `output_tokens` (per-request
    generated streams, request-id order) let main() report the top-1
    agreement rate of a quantized pass against its fp baseline."""
    import hashlib
    import math

    import jax

    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.quantization.serving import (kv_page_bytes,
                                                 normalize_quant_dtype)

    weight_dtype = normalize_quant_dtype(weight_dtype, "weight_dtype")
    kv_dtype = normalize_quant_dtype(kv_dtype, "kv_dtype")

    if config is None:
        config = gpt_mod.gpt_tiny(128)
    if params is None:
        params = gpt_mod.init_params(config, jax.random.key(seed))
    max_model_len = max_model_len or config.max_seq_len

    rng = np.random.RandomState(seed)
    max_prompt = max_model_len - max_new_tokens
    shared = None
    if shared_prefix_frac > 0.0:
        shared_len = min(max_prompt - 1,
                         max(page_size + page_size // 2, max_prompt // 2))
        shared = rng.randint(0, config.vocab_size, (shared_len,)).astype(np.int32)
    lens = rng.randint(1, max_prompt + 1, size=num_requests)
    prompts = []
    for n in lens:
        if shared is not None and rng.rand() < shared_prefix_frac:
            # 1 in 4 shared-prefix requests IS the bare prefix: completing it
            # registers its final partial page, so later extensions hit the
            # copy-on-write partial-page path, not just whole-page sharing
            tail = 0 if rng.rand() < 0.25 else \
                rng.randint(1, max_prompt - shared.size + 1)
            prompts.append(np.concatenate(
                [shared, rng.randint(0, config.vocab_size, (tail,))
                 .astype(np.int32)]) if tail else shared.copy())
        else:
            prompts.append(rng.randint(0, config.vocab_size, (n,))
                           .astype(np.int32))
    # Poisson process: exponential inter-arrival gaps at `request_rate` req/s
    gaps = (rng.exponential(1.0 / request_rate, size=num_requests)
            if np.isfinite(request_rate) else np.zeros(num_requests))
    arrivals = np.cumsum(gaps)

    # multi-turn chat sessions: clamp first-turn prompts so the LAST turn's
    # context (prompt + every reply + every fresh user chunk) still fits,
    # pre-draw the per-turn user chunks and each session's turn count NOW
    # (identical randomness across the tier/no-tier/spec/fuse comparison
    # passes), and size the host pool to hold every session's final context
    # so the capacity tier — not its eviction policy — is what is measured
    swap_pool_pages = None
    turn_chunks = {}
    session_turns = [1] * num_requests
    if multi_turn < 1:
        raise ValueError(f"multi_turn must be >= 1, got {multi_turn}")
    if multi_turn > 1:
        user_chunk = max(2, page_size // 2)
        reserve = (multi_turn - 1) * (max_new_tokens + user_chunk)
        if reserve >= max_prompt:
            raise ValueError(
                f"multi_turn={multi_turn} needs {reserve} growth tokens but "
                f"max_model_len leaves only {max_prompt} prompt tokens")
        prompts = [p[:max(1, max_prompt - reserve)] for p in prompts]
        session_turns = [multi_turn if rng.rand() < session_return_frac else 1
                         for _ in range(num_requests)]
        turn_chunks = {
            (s, t): rng.randint(0, config.vocab_size,
                                (user_chunk,)).astype(np.int32)
            for s in range(num_requests)
            for t in range(2, session_turns[s] + 1)}
        if kv_tier and not (oversubscribe and oversubscribe > 0):
            total_pages = sum(
                -(-(int(prompts[s].size) + (session_turns[s] - 1) *
                    (max_new_tokens + user_chunk) + max_new_tokens)
                  // page_size)
                for s in range(num_requests))
            swap_pool_pages = total_pages

    admission = "reservation"
    num_pages = None
    if oversubscribe and oversubscribe > 0:
        # shrink the pool so the submitted footprint is F x its token
        # capacity (clamped so the single largest request still fits, plus
        # one page of growth headroom) and admit optimistically — the whole
        # point is to make growth fail and preemption carry the load.  One
        # slot per request so LIVE TOKENS, not the slot count, bound
        # concurrency (with 4 slots a pool sized against 32 submitted
        # requests would never feel pressure); the F=1 pass through this
        # same sizing is the "unpressured" comparison baseline — identical
        # slot count, capacity == demand, zero (or near-zero) preemptions.
        admission = "optimistic"
        footprint = sum(int(p.size) + max_new_tokens for p in prompts)
        need = math.ceil(footprint / (oversubscribe * page_size))
        biggest = max(-(-(int(p.size) + max_new_tokens) // page_size)
                      for p in prompts)
        num_pages = max(need, biggest + 1) + 1      # +1: the null page
        num_slots = max(num_slots, num_requests)
        if kv_dtype == "int8":
            # equal-BYTE pool sizing: the fp pass's pool bytes at this F,
            # refilled with smaller int8 pages — the capacity win the
            # quantized pass must demonstrate (fewer preemptions at the
            # same HBM budget), reported as preemptions_per_step delta
            ratio = kv_page_bytes(config, page_size) / \
                kv_page_bytes(config, page_size, "int8")
            num_pages = int((num_pages - 1) * ratio) + 1

    eng = LLMEngine(params, config, num_slots=num_slots, page_size=page_size,
                    num_pages=num_pages,
                    max_model_len=max_model_len, prefill_chunk=prefill_chunk,
                    prefix_cache=prefix_cache, spec_len=spec_len, fuse=fuse,
                    admission=admission, preempt=preempt,
                    kv_tier=kv_tier, spill_dir=spill_dir,
                    swap_pool_pages=swap_pool_pages,
                    weight_dtype=weight_dtype, kv_dtype=kv_dtype,
                    mp=mp if mp and mp > 1 else None,
                    request_tracing=request_tracing,
                    # the ring must hold the whole timed run for the
                    # dispatches/sync aggregates, and every retired timeline
                    # must survive to the end of the run or the tracing-cost
                    # account undercounts its event volume
                    trace_ring=4096, trace_retention=None)
    prefill_chunk = eng.prefill_chunk   # "auto" resolved by the engine

    # warmup: compile every executable the timed section can reach so it
    # measures steady-state serving, not compilation.  Random (non-shared)
    # prompts keep the prefix cache out of bucket warmup; the identical pair
    # at the end compiles the chunk-tail + COW page-copy executables.
    wrng = np.random.RandomState(seed + 1)
    if prefill_chunk is None:
        # one prompt per reachable bucket (a bucket past max_prompt is still
        # reachable by shorter prompts — warm it with the longest admissible)
        for n in sorted({min(b, max_prompt) for b in eng.buckets}):
            eng.add_request(wrng.randint(0, config.vocab_size, (n,))
                            .astype(np.int32), max_new_tokens=1)
    else:
        n = min(max_prompt, prefill_chunk * 2 + 1)  # chunk + remainder path
        eng.add_request(wrng.randint(0, config.vocab_size, (n,))
                        .astype(np.int32), max_new_tokens=1)
    eng.run()
    if prefix_cache:
        lp = min(max_prompt - 2, page_size + page_size // 2 + 1)
        pair = wrng.randint(0, config.vocab_size, (lp + 2,)).astype(np.int32)
        eng.add_request(pair[:lp], max_new_tokens=1)
        eng.run()                       # donor registers its prompt pages
        eng.add_request(pair, max_new_tokens=1)
        eng.run()                       # extension: full-page share + COW
    # 1-token warmup requests pick their token at prefill and retire without
    # ever dispatching decode or verify — warm those two explicitly so their
    # compiles stay out of the timed section (the spec on/off ratio would
    # otherwise compare a compile-laden pass against a compile-light one)
    eng.warm_decode()
    eng.warm_spec()                     # verify executable (no-op spec off)
    eng.warm_swap()                     # swap gather/scatter (no-op unless
                                        # optimistic + preempt="swap")
    eng.reset_counters()

    pending = list(zip(arrivals, prompts, range(num_requests)))
    outs = []
    rid_session = {}        # rid -> (session, turn); turn 1 is the opener
    expected_total = sum(session_turns)
    # host-side capture only (spans + step timeline + metrics): a jax device
    # capture over a whole bench run would dominate the timed section and
    # turn the headline tokens/s into a profiler benchmark — for device
    # timelines, wrap a short window in `engine.trace(dir)` directly
    trace_ctx = eng.trace(trace_dir, device=False) if trace_dir \
        else contextlib.nullcontext()
    # the WARMED section runs under jax.transfer_guard("disallow"): every
    # executable is compiled, so any implicit host<->device transfer left in
    # the steady-state loop (a stray scalar h2d, an unplanned reshard under
    # mp) is a bug, and this is where it would silently tax every step — the
    # runtime twin of tpu_lint's TPL001/TPL005 static checks
    # crash hook: any exception out of the timed section — including the
    # drain-invariant asserts below — writes a postmortem debug bundle
    # (per-request states + timelines, step-trace ring, pool levels, stats,
    # metrics snapshot) before propagating, so an engine that wedged or
    # leaked pages 40 minutes into a soak is debuggable from the artifact
    # instead of reproducible-if-lucky
    try:
        with trace_ctx, jax.transfer_guard("disallow"):
            # clock starts AFTER trace-context entry (mkdir + profiler start)
            # and stops BEFORE its exit (trace serialization): capture
            # setup/teardown must not count against the traced pass's tokens/s
            t0 = time.perf_counter()
            while pending or eng.has_work:
                now = time.perf_counter() - t0
                while pending and pending[0][0] <= now:
                    _, p, s = pending.pop(0)
                    rid_session[eng.add_request(
                        p, max_new_tokens=max_new_tokens)] = (s, 1)
                if eng.has_work:
                    fin = eng.step()
                    outs.extend(fin)
                    # returning sessions: the moment a turn finishes, the
                    # session comes back with its WHOLE conversation as the
                    # next prompt (+ a fresh pre-drawn user chunk) — the
                    # multi-turn traffic shape the KV tier restores
                    for o in fin:
                        s, t = rid_session[o.request_id]
                        if t < session_turns[s]:
                            nxt = np.concatenate(
                                [np.asarray(o.prompt, np.int32),
                                 np.asarray(o.token_ids, np.int32),
                                 turn_chunks[(s, t + 1)]])
                            rid_session[eng.add_request(
                                nxt, max_new_tokens=max_new_tokens)] = \
                                (s, t + 1)
                elif pending:
                    time.sleep(min(pending[0][0] - now, 0.01))
            dt = time.perf_counter() - t0
        assert len(outs) == expected_total, (len(outs), expected_total)
        # drain invariant: free/LRU/in-use/swapped page partition exact, zero
        # leaked pages — the oversubscribed run's hard acceptance bar, and
        # cheap enough to assert on every run
        eng.cache.check_invariants()
        assert eng.cache.swapped_page_count == 0, "host swap pool leaked pages"
    # tpu-lint: disable=TPL006 -- postmortem hook, not a fallback: ANY escape from the timed section (asserts included) writes the debug bundle and re-raises unconditionally, nothing is swallowed
    except BaseException:
        if debug_bundle_dir:
            # the hook fires exactly when engine state may be wrecked: a
            # failure in the dump itself must not mask the original crash
            try:
                path = eng.dump_debug_bundle(debug_bundle_dir)
                print(f"[bench_serve] crash/invariant failure: debug bundle "
                      f"written to {path}", file=sys.stderr)
            except Exception as dump_err:
                print(f"[bench_serve] crash/invariant failure; debug bundle "
                      f"dump ALSO failed: {dump_err!r}", file=sys.stderr)
        raise

    st = eng.stats()
    at_rest = eng.at_rest_bytes()   # cached cost account, zero extra traces
    lat = st["latency"]     # engine-side lifecycle histograms, seconds
    # EMITTED decode tokens only — idle slots in ramp-up/drain iterations are
    # not useful work and would overstate throughput at low arrival rates
    # (with spec on, an accepted draft emits several tokens per slot-step)
    decode_tokens = st["decode_tokens"]
    # multi-turn: order and key streams by (session, turn) — request ids are
    # assigned in FINISH order, which scheduling may legitimately permute
    # between comparison passes; parity is about conversations, not id
    # assignment.  Single-turn keeps the PR-3 id-keyed digest byte-for-byte.
    if multi_turn > 1:
        order_key = lambda o: rid_session[o.request_id]     # noqa: E731
        ident = lambda o: rid_session[o.request_id]         # noqa: E731
    else:
        order_key = lambda o: o.request_id                  # noqa: E731
        ident = lambda o: (o.request_id,)                   # noqa: E731
    digest = hashlib.sha256()
    for o in sorted(outs, key=order_key):
        # id + length delimit each stream: tokens redistributed across
        # request boundaries must not collide to the same digest
        digest.update(np.asarray(list(ident(o)) + [len(o.token_ids)],
                                 np.int64).tobytes())
        digest.update(np.asarray(o.token_ids, np.int64).tobytes())
    # returning-turn view (turn >= 2): the requests whose prefill the tier
    # exists to eliminate — prefilled = prompt minus whatever admission
    # served from cache (device share, tier restore, COW fraction)
    returning = [o for o in outs if rid_session[o.request_id][1] > 1]
    returning_prefilled = sum(
        int(np.asarray(o.prompt).size) - int(o.cached_tokens)
        for o in returning)
    r_ttfts = [o.ttft_s for o in returning if o.ttft_s is not None]
    returning_ttft_p50_ms = round(median(r_ttfts) * 1e3, 2) if r_ttfts \
        else None
    # an mp mesh uses exactly mp chips; single-chip serving uses one program
    # on however many devices the host exposes (forced-CPU CI counts them all)
    n_chips = eng.mp if eng.mp > 1 else max(1, len(jax.devices()))
    # dispatch/sync aggregates from the step timeline: decode-path program
    # dispatches (fused/decode/verify/chunk-interleave; the admission-time
    # one-shot prefill is the cold path) and blocking host-sync time, both
    # averaged over the steps that dispatched anything — the one-dispatch
    # claim in numbers (fused: 1.0; unfused busy steps: up to 3)
    timeline = eng.step_trace()
    busy = [r for r in timeline if r["dispatches"] > 0]
    dispatches_per_step = (sum(r["dispatches"] for r in busy) / len(busy)
                           if busy else 0.0)
    host_sync_ms = (sum(r["sync_ms"] for r in timeline) / len(busy)
                    if busy else 0.0)
    # static roofline prediction for the decode-side program at THIS
    # engine's shapes (`analysis/cost_model.py`): traced abstractly after
    # the timed section — no dispatch, no compile, program counts untouched.
    # model_error = measured/predicted; on TPU the dispatch is device-bound
    # and the ratio is meaningful, on the CPU smoke host scheduling
    # dominates and it is only sanity-bounded.
    from paddle_tpu.analysis.cost_model import device_spec
    dspec = device_spec()
    # `predicted_step_ms` is the engine's own cached roofline (armed by
    # warm_decode above, through the SAME engine_step_cost account
    # tools/tpu_cost.py prints) — the live roofline_drift gauge divides by
    # exactly this number, so the bench and the gauge cannot disagree
    predicted_ms = eng.predicted_step_ms
    measured_ms = (sum(r["dur_s"] for r in busy) / len(busy) * 1e3
                   if busy else 0.0)
    # deterministic tracing-cost account: wall-clock A/Bs on a shared CI box
    # swing ±10%+ run-over-run, which no small-n estimator can squeeze under
    # a <2% bar — so the bar is held by DIRECT accounting instead.  Count the
    # timeline stamps this run actually made (event volume is bounded by
    # construction: admission-/chunk-/verify-granular, never per-decode-token,
    # and every exemplar attach coincides with at most one stamp), then price
    # one stamp + one exemplar-carrying observe with a post-run microbench of
    # those exact primitives.  events x unit-cost / timed-section is a
    # reproducible upper bound on the plane's throughput tax — zero
    # instrumentation inside the timed section itself.  The wall-clock pair
    # ratio main() still reports corroborates it (and byte-exact parity is
    # exact either way); this is the number the <2% acceptance bar reads.
    tracing_events = sum(len(o.trace.events) for o in outs
                         if o.trace is not None)
    tracing_host_ms = tracing_overhead_measured = None
    if request_tracing:
        from paddle_tpu.inference.metrics import Histogram
        from paddle_tpu.inference.tracing import RequestTrace
        tr = RequestTrace(0)
        h = Histogram("tracing_unit_cost", buckets=[0.01, 0.1, 1.0])
        n_ub = 10000
        t_ub = time.perf_counter()
        for _ in range(n_ub):
            # one clock read + dict/list append (RequestTrace.event) + one
            # exemplar label build + attach-carrying observe — the full
            # differential of a tracing-on step vs tracing-off, measured on
            # a representative high-attribute event
            tr.event(time.monotonic(), "spec_verify",
                     drafted=4, accepted=2, emitted=3)
            h.observe(0.05, exemplar={"request_id": "0",
                                      "trace": "/requests/0"})
            if len(tr.events) >= 512:   # keep the append O(1), list bounded
                del tr.events[:]
        per_op_s = (time.perf_counter() - t_ub) / n_ub
        tracing_host_ms = tracing_events * per_op_s * 1e3
        tracing_overhead_measured = tracing_host_ms / (dt * 1e3)
    return {
        "mp": eng.mp,
        "fused": eng.fused,
        "request_tracing": request_tracing,
        # the always-on plane's cost, directly accounted (see above): stamp
        # count, its priced host time, and that time over the timed section —
        # the deterministic side of the <2% bar
        "tracing_events": tracing_events,
        "tracing_host_ms": round(tracing_host_ms, 4)
                           if tracing_host_ms is not None else None,
        "tracing_overhead_measured": round(tracing_overhead_measured, 6)
                                     if tracing_overhead_measured is not None
                                     else None,
        # quantized-serving surface: knobs, at-rest pool bytes (the capacity
        # number) and the per-request streams main() scores agreement on
        "weight_dtype": st["weight_dtype"],
        "kv_dtype": st["kv_dtype"],
        "kv_pool_bytes": st["kv_pool_bytes"],
        # vocab-sharded head surface: at-rest param placement per device from
        # the engine's cached cost account (zero extra traces).  At mp>=2 the
        # floor is replicated_bytes_per_device STRICTLY below the fp wte size
        # — the "replicated embedding ceiling" this layout retired.
        "replicated_bytes_per_device": at_rest["replicated_bytes_per_device"],
        "sharded_bytes_per_device": at_rest["sharded_bytes_per_device"],
        "wte_bytes": at_rest["wte_bytes"],
        "intake_swap_rejects": st["intake_swap_rejects"],
        "output_tokens": [list(map(int, o.token_ids))
                          for o in sorted(outs, key=order_key)],
        # KV-tier / multi-turn surface: tier occupancy + spill/restore
        # traffic, the rolling-hash partial-index hit count, and the
        # returning-session (turn >= 2) view the tier's win is measured on
        "kv_tier": st["kv_tier"]["enabled"],
        "spill_dir": spill_dir,
        "multi_turn": multi_turn,
        "session_return_frac": session_return_frac
                               if multi_turn > 1 else None,
        "kv_tier_pages_host": st["kv_tier"]["pages_host"],
        "kv_tier_pages_disk": st["kv_tier"]["pages_disk"],
        "kv_tier_spills": st["kv_tier"]["spills"],
        "resume_hits": st["kv_tier"]["restores"],
        "resume_restored_tokens": st["kv_tier"]["restored_tokens"],
        "partial_page_hits": st["kv_tier"]["partial_page_hits"],
        "returning_requests": len(returning),
        "returning_prefilled_tokens": returning_prefilled,
        "returning_ttft_p50_ms": returning_ttft_p50_ms,
        "dispatches_per_step": round(dispatches_per_step, 3),
        "host_sync_ms_per_step": round(host_sync_ms, 4),
        "predicted_step_ms": round(predicted_ms, 4),
        "measured_step_ms": round(measured_ms, 4),
        "model_error": round(measured_ms / predicted_ms, 3)
                       if predicted_ms > 0 else None,
        "device_spec": dspec.name,
        # live signal plane (health & signals PR): the steady-state drift
        # gauge (EWMA measured / predicted — the run-long average above is
        # the bench's number, this is what a scrape would see), recompile
        # anomalies, and the health state the run drained at
        "roofline_drift": st["roofline"]["drift"],
        "steady_state_recompiles": st["roofline"]["steady_state_recompiles"],
        "health_state": st["health"]["state"],
        "decode_tokens_per_sec_per_chip": round(decode_tokens / dt / n_chips, 1),
        "generated_tokens_per_sec": round(
            expected_total * max_new_tokens / dt, 1),
        # goodput: tokens that made it into FINAL outputs per second —
        # preempted-and-replayed prefill work earns nothing here, so the
        # recompute tax shows up as goodput < decode throughput
        "goodput_tokens_per_sec": round(
            sum(len(o.token_ids) for o in outs) / dt, 1),
        # SLO surface next to goodput: attainment over retired deadline-
        # bearing requests (None when the stream carries no deadlines —
        # this offline bench's default) + final-output tokens per priority
        "slo": st["slo"],
        "admission": st["admission"],
        "preempt_mode": st["preempt"],
        "oversubscribe": oversubscribe,
        "kv_num_pages": eng.cache.num_pages,
        "preemptions": st["preemptions"],
        "preemptions_per_step": round(
            st["preemptions"] / max(st["engine_steps"], 1), 4),
        "preempt_swaps": st["preempt_swaps"],
        "preempt_recomputes": st["preempt_recomputes"],
        "swapped_pages": st["swapped_pages"],
        "swap_ms": round(st["swap_ms"], 3),
        "recomputed_tokens": st["recomputed_tokens"],
        "timeouts": st["timeouts"],
        "rejected_requests": st["rejected_requests"],
        "swap_executables": st["swap_executables"],
        "requests": num_requests,
        "elapsed_s": round(dt, 3),
        "ttft_p50_ms": round(lat["ttft_s"]["p50"] * 1e3, 2),
        "ttft_p99_ms": round(lat["ttft_s"]["p99"] * 1e3, 2),
        "tpot_p50_ms": round(lat["tpot_s"]["p50"] * 1e3, 2),
        "tpot_p99_ms": round(lat["tpot_s"]["p99"] * 1e3, 2),
        "queue_p50_ms": round(lat["queue_s"]["p50"] * 1e3, 2),
        "queue_p99_ms": round(lat["queue_s"]["p99"] * 1e3, 2),
        "e2e_p50_ms": round(lat["e2e_s"]["p50"] * 1e3, 2),
        "e2e_p99_ms": round(lat["e2e_s"]["p99"] * 1e3, 2),
        "prefix_hit_rate": round(st["prefix_hit_rate"], 4),
        "prefix_cached_tokens": st["prefix_cached_tokens"],
        "prefilled_tokens": st["prefilled_tokens"],
        "cow_page_copies": st["cow_page_copies"],
        "prefix_evictions": st["prefix_evictions"],
        "decode_iters": st["decode_iterations"],
        "prefill_chunks": st["prefill_chunks"],
        "decode_executables": st["decode_executables"],
        "verify_executables": st["verify_executables"],
        "prefill_executables": st["prefill_executables"],
        "copy_executables": st["copy_executables"],
        "buckets": st["buckets"],
        "prefill_chunk": prefill_chunk,
        "shared_prefix_frac": shared_prefix_frac,
        "spec_len": spec_len,
        "verify_steps": st["verify_steps"],
        "spec_events": st["spec_events"],
        "accepted_per_step": round(st["accepted_per_step"], 3),
        "spec_drafted_tokens": st["spec_drafted_tokens"],
        "spec_accepted_tokens": st["spec_accepted_tokens"],
        "outputs_digest": digest.hexdigest(),
        "kv_token_capacity": st["kv_token_capacity"],
        "dense_token_footprint": st["dense_token_footprint"],
        "trace_dir": trace_dir,
        # full registry snapshot (counters/gauges/histogram summaries) — the
        # scrape-shaped view, embedded so a bench JSON is self-contained
        "metrics": eng.metrics.snapshot(),
    }


def run_fleet_bench(*, replicas=2, router="affinity", num_sessions=5,
                    turns=3, max_new_tokens=5, seed=0, config=None,
                    params=None, num_slots=4, page_size=8,
                    prefill_chunk=16):
    """Multi-turn chat sessions routed through the dp `EngineFleet` — the
    `--replicas N --router ...` axis of the serving bench.

    Three passes over the SAME pre-drawn session stream (CPU-smoke shaped
    regardless of platform — the fleet claims under test are routing and
    program-sharing, not device throughput): a single-engine baseline (the
    parity oracle), a `replicas`-wide fleet under the requested `router`,
    and a `round_robin` fleet — what a cache-blind balancer in front of N
    independent processes does.  `num_sessions` is odd by default so
    round-robin's turn-2 assignment SHIFTS off the turn-1 one (an even
    count would park every session back on its turn-1 replica by accident
    and hide exactly the blindness being measured).

    Returned keys (merged into the schema-v3 trajectory row):

    - `fleet_generated_tokens_per_sec` + `replica_balance` (min/max
      submitted across replicas) for the requested-router pass;
    - the A/B: `affinity_prefix_hit_rate` vs `round_robin_prefix_hit_rate`
      — cached fraction of RETURNING-turn (turn >= 2) prompt tokens, the
      traffic affinity exists for — folded into
      `affinity_prefix_hit_ratio` = (1 + affinity) / (1 + round_robin), a
      smoothed odds ratio that stays finite when the blind side hits
      nothing; its `>= 1.0` floor (SERVE_PERF_FLOORS) says cache-aware
      routing never hits LESS than cache-blind;
    - `affinity_returning_ttft_p50_ms` vs
      `round_robin_returning_ttft_p50_ms`: the wall-clock corroboration —
      a returning turn routed away from its KV re-prefills the whole
      conversation and pays for it in time-to-first-token;
    - `fleet_parity`: every pass's (session, turn) token streams byte-equal
      to the single-engine baseline — routing must never change tokens;
    - `fleet_shared_executables`: every pass's replicas ran the leader's
      compiled set (`EngineFleet` adoption — dp replication adds zero
      programs; tools/check_program_count.py holds the same bar)."""
    import jax

    from paddle_tpu.inference.router import EngineFleet
    from paddle_tpu.models import gpt as gpt_mod

    if turns < 2:
        raise ValueError(f"fleet bench needs returning turns (turns >= 2), "
                         f"got {turns}")
    if config is None:
        config = gpt_mod.gpt_tiny(64)
    if params is None:
        params = gpt_mod.init_params(config, jax.random.key(seed))
    max_model_len = config.max_seq_len
    ekw = dict(num_slots=num_slots, page_size=page_size,
               max_model_len=max_model_len, prefill_chunk=prefill_chunk,
               spec_len=0, seed=seed)

    # pre-draw every session's first prompt and per-turn user chunks ONCE:
    # all passes replay the identical stream, so hit-rate/TTFT deltas are
    # pure routing policy
    rng = np.random.RandomState(seed)
    user_chunk = max(2, page_size // 2)
    reserve = (turns - 1) * (max_new_tokens + user_chunk) + max_new_tokens
    first_max = max_model_len - reserve
    if first_max <= page_size:
        raise ValueError(f"turns={turns} leaves only {first_max} first-turn "
                         f"prompt tokens at max_model_len={max_model_len}")
    sessions = [f"s{i}" for i in range(num_sessions)]
    prompts = {s: rng.randint(0, config.vocab_size,
                              (int(rng.randint(page_size, first_max + 1)),)
                              ).astype(np.int32).tolist()
               for s in sessions}
    chunks = {(s, t): rng.randint(0, config.vocab_size, (user_chunk,)
                                  ).astype(np.int32).tolist()
              for s in sessions for t in range(2, turns + 1)}
    warm_rng = np.random.RandomState(seed + 1)
    warm_prompt = warm_rng.randint(0, config.vocab_size,
                                   (2 * page_size + 3,)).astype(np.int32)
    warm_tail = warm_rng.randint(0, config.vocab_size,
                                 (user_chunk + max_new_tokens,)
                                 ).astype(np.int32)

    def _pass(n_replicas, policy):
        fleet = EngineFleet(params, config, replicas=n_replicas,
                            router=policy, engine_kwargs=ekw)
        shared = fleet.shared_executables()
        # compile outside the timed section: a throwaway prompt through the
        # leader covers the chunk-prefill + fused-decode shapes, and a
        # second prompt EXTENDING it covers the prefix-hit prefill lane
        # (page mapping + partial-page restore) every returning turn rides
        # — without that the first cached prefill's compile lands in the
        # timed section and charges the affinity side ~100 ms of TTFT it
        # did not earn.  Adopted executables make these compiles fleet-wide.
        leader = next(iter(fleet.engines.values()))
        for p in (warm_prompt, np.concatenate([warm_prompt, warm_tail])):
            leader.add_request(p, max_new_tokens=max_new_tokens)
            while leader.has_work:
                leader.step()
        fleet.warm()
        for eng in fleet.engines.values():
            eng.reset_counters()
        fleet.start()
        outs, plen = {}, {}
        convs = {s: list(p) for s, p in prompts.items()}
        t0 = time.perf_counter()
        for t in range(1, turns + 1):
            handles = {}
            for s in sessions:
                if t > 1:
                    convs[s] = (convs[s] + list(outs[(s, t - 1)].token_ids)
                                + chunks[(s, t)])
                plen[(s, t)] = len(convs[s])
                handles[s] = fleet.submit(np.asarray(convs[s], np.int32),
                                          session=s,
                                          max_new_tokens=max_new_tokens)
            for s, h in handles.items():
                out = fleet.result(h, timeout=300.0)
                if out is None:
                    raise RuntimeError(f"fleet bench: session {s} turn {t} "
                                       f"timed out on {h}")
                outs[(s, t)] = out
        dt = time.perf_counter() - t0
        if not fleet.drain(timeout=60.0):
            raise RuntimeError("fleet bench: drain timed out")
        fleet.check_invariants()
        fstats = fleet.stats()
        fleet.stop()
        returning = [k for k in outs if k[1] >= 2]
        ret_cached = sum(int(outs[k].cached_tokens) for k in returning)
        ret_prompt = sum(plen[k] for k in returning)
        ttfts = sorted(float(outs[k].ttft_s) for k in returning
                       if outs[k].ttft_s is not None)
        submitted = [d["submitted"] for d in fstats["per_engine"].values()]
        return {
            "digest": {f"{s}|{t}": [int(x) for x in o.token_ids]
                       for (s, t), o in outs.items()},
            "gen": sum(len(o.token_ids) for o in outs.values()),
            "dt": dt,
            "hit": ret_cached / max(ret_prompt, 1),
            "ttft_p50_ms": median(ttfts) * 1e3 if ttfts else None,
            "balance": round(min(submitted) / max(max(submitted), 1), 3),
            "shed": fstats["shed"],
            "shared": shared,
        }

    single = _pass(1, "affinity")
    passes = {"affinity": _pass(replicas, "affinity"),
              "round_robin": _pass(replicas, "round_robin")}
    if router not in passes:
        passes[router] = _pass(replicas, router)
    req, aff, rr = passes[router], passes["affinity"], passes["round_robin"]
    return {
        "replicas": replicas,
        "router": router,
        "fleet_sessions": num_sessions,
        "fleet_turns": turns,
        "fleet_generated_tokens_per_sec": round(
            req["gen"] / max(req["dt"], 1e-9), 2),
        "replica_balance": req["balance"],
        "fleet_shed": req["shed"],
        "affinity_prefix_hit_rate": round(aff["hit"], 4),
        "round_robin_prefix_hit_rate": round(rr["hit"], 4),
        "affinity_prefix_hit_ratio": round(
            (1.0 + aff["hit"]) / (1.0 + rr["hit"]), 4),
        "affinity_returning_ttft_p50_ms": (
            None if aff["ttft_p50_ms"] is None
            else round(aff["ttft_p50_ms"], 2)),
        "round_robin_returning_ttft_p50_ms": (
            None if rr["ttft_p50_ms"] is None
            else round(rr["ttft_p50_ms"], 2)),
        "fleet_parity": all(p["digest"] == single["digest"]
                            for p in passes.values()),
        "fleet_shared_executables": single["shared"] and all(
            p["shared"] for p in passes.values()),
    }


def run_disagg_bench(*, roles="P:D", num_sessions=4, turns=2,
                     max_new_tokens=5, seed=0, config=None, params=None,
                     num_slots=4, page_size=8, prefill_chunk=16):
    """Disaggregated prefill/decode serving — the `--disagg P:D` axis.

    Replays ONE pre-drawn multi-turn session stream through three setups
    and one restart scenario (CPU-smoke shaped on every platform — the
    claims under test are handoff correctness and latency, not device
    throughput):

    - a single-engine oracle (the parity baseline);
    - a colocated 2-replica affinity fleet (what PR 16 ships) — its decode
      TPOT carries the prefill interference a role split removes;
    - a `roles`-partitioned disaggregated fleet: prefill replicas export
      finished prompts through the shared durable tier store, decode
      replicas one-scatter restore them (`handoff_p50/p99_ms` measure
      prefill-submit -> decode-index-refresh wall time);
    - an engine RESTART: engine A serves turn 1 on a private `spill_dir`,
      exports, and is destroyed; a fresh engine B on the SAME dir re-
      attaches the serialized index at construction and serves the
      returning turn (`restart_restored_tokens` — tokens tier-restored
      instead of re-prefilled — and `restart_ttft_ms`).

    `disagg_parity` is byte-exact: colocated, disaggregated AND the
    restarted engine's returning turn must all reproduce the oracle's
    token streams.  `interference_tpot_delta_ms` (colocated decode-TPOT
    p50 minus the disagg decode pool's) is report-only — wall clock on a
    shared box."""
    import tempfile

    import jax

    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.inference.router import EngineFleet
    from paddle_tpu.models import gpt as gpt_mod

    if turns < 2:
        raise ValueError(f"disagg bench needs returning turns (turns >= 2), "
                         f"got {turns}")
    if config is None:
        config = gpt_mod.gpt_tiny(64)
    if params is None:
        params = gpt_mod.init_params(config, jax.random.key(seed))
    max_model_len = config.max_seq_len
    ekw = dict(num_slots=num_slots, page_size=page_size,
               max_model_len=max_model_len, prefill_chunk=prefill_chunk,
               spec_len=0, seed=seed)

    rng = np.random.RandomState(seed)
    user_chunk = max(2, page_size // 2)
    reserve = (turns - 1) * (max_new_tokens + user_chunk) + max_new_tokens
    first_max = max_model_len - reserve
    if first_max <= page_size:
        raise ValueError(f"turns={turns} leaves only {first_max} first-turn "
                         f"prompt tokens at max_model_len={max_model_len}")
    sessions = [f"s{i}" for i in range(num_sessions)]
    prompts = {s: rng.randint(0, config.vocab_size,
                              (int(rng.randint(page_size, first_max + 1)),)
                              ).astype(np.int32).tolist()
               for s in sessions}
    chunks = {(s, t): rng.randint(0, config.vocab_size, (user_chunk,)
                                  ).astype(np.int32).tolist()
              for s in sessions for t in range(2, turns + 1)}
    warm_rng = np.random.RandomState(seed + 1)
    warm_prompt = warm_rng.randint(0, config.vocab_size,
                                   (2 * page_size + 3,)).astype(np.int32)
    warm_tail = warm_rng.randint(0, config.vocab_size,
                                 (user_chunk + max_new_tokens,)
                                 ).astype(np.int32)

    def _warm(fleet):
        leader = next(iter(fleet.engines.values()))
        for p in (warm_prompt, np.concatenate([warm_prompt, warm_tail])):
            leader.add_request(p, max_new_tokens=max_new_tokens)
            while leader.has_work:
                leader.step()
        fleet.warm()
        for eng in fleet.engines.values():
            eng.reset_counters()

    def _pass(fleet):
        """Replay the stream through `fleet`; returns digest + decode-side
        TPOT p50 (ms) + the fleet's own disagg/handoff stats."""
        _warm(fleet)
        fleet.start()
        outs = {}
        convs = {s: list(p) for s, p in prompts.items()}
        for t in range(1, turns + 1):
            handles = {}
            for s in sessions:
                if t > 1:
                    convs[s] = (convs[s] + list(outs[(s, t - 1)].token_ids)
                                + chunks[(s, t)])
                handles[s] = fleet.submit(np.asarray(convs[s], np.int32),
                                          session=s,
                                          max_new_tokens=max_new_tokens)
            for s, h in handles.items():
                out = fleet.result(h, timeout=300.0)
                if out is None:
                    raise RuntimeError(f"disagg bench: session {s} turn {t} "
                                       f"timed out on {h}")
                outs[(s, t)] = out
        if not fleet.drain(timeout=60.0):
            raise RuntimeError("disagg bench: drain timed out")
        fleet.check_invariants()
        fstats = fleet.stats()
        fleet.stop()
        # decode-side TPOT: the decode pool's histograms under roles, every
        # replica's otherwise (colocated replicas all decode)
        dec = fleet.decode_pool or list(fleet.engines)
        tpots = [fleet.engines[l]._h_tpot for l in dec
                 if fleet.engines[l]._h_tpot.count]
        tpot_ms = (median([h.percentile(50.0) for h in tpots]) * 1e3
                   if tpots else None)
        return {
            "digest": {f"{s}|{t}": [int(x) for x in o.token_ids]
                       for (s, t), o in outs.items()},
            "tpot_p50_ms": tpot_ms,
            "disagg": fstats.get("disagg"),
        }

    oracle = _pass(EngineFleet(params, config, replicas=1,
                               engine_kwargs=dict(ekw)))
    coloc = _pass(EngineFleet(params, config, replicas=2, router="affinity",
                              engine_kwargs=dict(ekw)))
    disagg = _pass(EngineFleet(params, config, roles=roles,
                               engine_kwargs=dict(ekw)))

    # ---- engine restart: sessions must outlive a process ------------------
    spill_dir = tempfile.mkdtemp(prefix="kvrestart_")
    s0 = sessions[0]
    eng_a = LLMEngine(params, config, spill_dir=spill_dir, **ekw)
    conv = list(prompts[s0])
    out1 = eng_a.result(eng_a.add_request(np.asarray(conv, np.int32),
                                          max_new_tokens=max_new_tokens))
    conv = conv + [int(x) for x in out1.token_ids]
    eng_a.export_prefix(np.asarray(conv, np.int32))
    del eng_a
    # a FRESH engine on the same spill_dir re-attaches the serialized index
    # at construction — the returning turn restores with one scatter
    eng_b = LLMEngine(params, config, spill_dir=spill_dir, **ekw)
    # warm B's executables on throwaway prompts so restart_ttft_ms prices
    # the restore path, not the restarted process's cold compiles
    for p in (warm_prompt, np.concatenate([warm_prompt, warm_tail])):
        eng_b.result(eng_b.add_request(p, max_new_tokens=max_new_tokens))
    eng_b.warm_swap()
    eng_b.reset_counters()
    conv2 = conv + chunks[(s0, 2)]
    out2 = eng_b.result(eng_b.add_request(np.asarray(conv2, np.int32),
                                          max_new_tokens=max_new_tokens))
    bst = eng_b.stats()
    restart_ok = ([int(x) for x in out1.token_ids] == oracle["digest"][
                      f"{s0}|1"] and
                  [int(x) for x in out2.token_ids] == oracle["digest"][
                      f"{s0}|2"])
    del eng_b

    dstats = disagg["disagg"] or {}
    delta = (None if coloc["tpot_p50_ms"] is None or
             disagg["tpot_p50_ms"] is None
             else round((coloc["tpot_p50_ms"] - disagg["tpot_p50_ms"]), 3))
    return {
        "handoff_p50_ms": dstats.get("handoff_p50_ms"),
        "handoff_p99_ms": dstats.get("handoff_p99_ms"),
        "handoff_count": dstats.get("handoffs", 0),
        "handoff_skips": dstats.get("handoff_skips", 0),
        "handoff_degrades": dstats.get("handoff_degrades", 0),
        "colocated_tpot_p50_ms": coloc["tpot_p50_ms"],
        "disagg_tpot_p50_ms": disagg["tpot_p50_ms"],
        "interference_tpot_delta_ms": delta,
        "restart_restored_tokens": int(
            bst["kv_tier"]["restored_tokens"]),
        "restart_ttft_ms": (None if out2.ttft_s is None
                            else round(float(out2.ttft_s) * 1e3, 2)),
        "disagg_parity": (coloc["digest"] == oracle["digest"] and
                          disagg["digest"] == oracle["digest"] and
                          restart_ok),
    }


def main():
    import argparse
    import os

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mp", type=int, default=1,
                    help="tensor-parallel degree: shard the serving model "
                         "over the first N chips (heads + FFN Megatron-style;"
                         " on CPU, simulate chips with XLA_FLAGS="
                         "--xla_force_host_platform_device_count=8)")
    ap.add_argument("--shared-prefix-frac", type=float, default=0.0,
                    help="fraction of requests sharing a common prompt prefix")
    ap.add_argument("--prefill-chunk", type=str, default=None,
                    help="Sarathi chunked prefill with this chunk length "
                         "(default: bucketed one-shot prefill); 'auto' lets "
                         "the engine pick spec_len+1 (one page when spec is "
                         "off) so the chunk lane never widens the fused "
                         "program past what verify already needs")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable copy-on-write prefix page sharing")
    ap.add_argument("--no-fuse", action="store_true",
                    help="disable the fused one-dispatch step: legacy "
                         "three-program scheduling (decode + chunk + verify "
                         "programs, host-side sampling) — the A/B baseline; "
                         "also skips the fused-vs-unfused comparison pass")
    ap.add_argument("--spec-len", type=int, default=4,
                    help="speculative decoding draft length (n-gram "
                         "self-drafting + one K+1-token verify executable)")
    ap.add_argument("--no-spec", action="store_true",
                    help="disable speculative decoding (also skips the "
                         "spec-off comparison pass)")
    ap.add_argument("--oversubscribe", type=float, default=0.0,
                    help="shrink the page pool so the submitted token "
                         "footprint is F x its capacity and admit "
                         "optimistically (prompt footprint only, token-"
                         "granular growth, preemption under pressure); "
                         "also runs an unpressured comparison pass "
                         "reporting goodput_ratio + byte-exact "
                         "oversubscribe_parity")
    ap.add_argument("--weight-dtype", choices=("bf16", "int8"),
                    default="bf16",
                    help="serving param dtype: int8 = weight-only symmetric "
                         "per-channel PTQ (dequantized per block inside the "
                         "layer scan; at-rest param HBM drops ~2x vs bf16, "
                         "~4x vs fp32); also runs an fp comparison pass on "
                         "the same stream reporting top-1 agreement")
    ap.add_argument("--kv-dtype", choices=("bf16", "int8"),
                    default="bf16",
                    help="KV page pool dtype: int8 = quantized pages + "
                         "per-token scale lanes, dequantized per page on "
                         "read inside the paged-attention kernels; under "
                         "--oversubscribe the int8 pool is sized to the "
                         "SAME HBM bytes (more pages), so the capacity win "
                         "shows as the preemptions_per_step delta vs the fp "
                         "comparison pass")
    ap.add_argument("--preempt", choices=("recompute", "swap"),
                    default="recompute",
                    help="preemption mechanism under --oversubscribe: "
                         "release + replay prompt+generated through the "
                         "prefix cache (recompute), or park victim KV in a "
                         "host-side pool and restore it by one h2d scatter "
                         "(swap) — the A/B axis")
    ap.add_argument("--multi-turn", type=int, default=1,
                    help="multi-turn chat sessions: each request becomes a "
                         "session that re-submits its whole conversation "
                         "(prompt + reply + a fresh user chunk) up to N "
                         "turns, follow-ups enqueued the moment the "
                         "previous turn finishes; with the KV tier on, "
                         "evicted session KV restores by one scatter "
                         "instead of re-prefilling — also runs a "
                         "--no-kv-tier comparison pass on the same stream "
                         "reporting returning_prefilled_drop + byte-exact "
                         "kv_tier_parity")
    ap.add_argument("--session-return-frac", type=float, default=1.0,
                    help="fraction of sessions that return for turns past "
                         "the first (multi-turn mode)")
    ap.add_argument("--no-kv-tier", action="store_true",
                    help="disable KV tiering: evicted prefix pages are "
                         "dropped (the PR-10 behavior) instead of spilling "
                         "to the bounded host tier; also skips the tier "
                         "comparison pass")
    ap.add_argument("--spill-dir", type=str, default=None,
                    help="disk tier beneath the host KV tier: over-budget "
                         "spilled prefixes serialize here (npz per page) "
                         "instead of being dropped, and restore "
                         "transparently on a hit")
    ap.add_argument("--replicas", type=int, default=1,
                    help="dp engine-fleet width: > 1 adds the fleet passes "
                         "(run_fleet_bench) — a multi-turn session stream "
                         "routed through EngineFleet under --router, plus "
                         "the round-robin cache-blind baseline and a "
                         "single-engine parity oracle on the same stream; "
                         "the row gains the fleet axes + "
                         "affinity-vs-round-robin prefix-hit/TTFT A/B "
                         "(CPU-smoke shaped on every platform)")
    ap.add_argument("--router", choices=("affinity", "round_robin",
                                         "least_loaded"),
                    default="affinity",
                    help="fleet routing policy for the requested pass; the "
                         "affinity-vs-round-robin A/B always runs both "
                         "sides regardless")
    ap.add_argument("--disagg", type=str, default=None, metavar="P:D",
                    help="disaggregated prefill/decode passes "
                         "(run_disagg_bench) under this role split (e.g. "
                         "'P:D', '2P:2D'): the same pre-drawn multi-turn "
                         "stream runs colocated vs disaggregated vs a "
                         "single-engine oracle (byte-exact disagg_parity), "
                         "plus an engine-restart restore sub-pass; the row "
                         "gains handoff p50/p99, the prefill-interference "
                         "TPOT delta and the restart axes")
    ap.add_argument("--request-rate", type=float, default=None,
                    help="Poisson arrival rate in req/s (default: offline)")
    ap.add_argument("--no-request-tracing", action="store_true",
                    help="disable per-request timelines + metric exemplars "
                         "(the always-on observability plane); the default "
                         "run replays the stream untraced to report "
                         "tracing_overhead + byte-exact tracing_parity — "
                         "the <2%% bar the plane holds")
    ap.add_argument("--tracing-reps", type=int, default=1,
                    help="on/off pairs in the tracing A/B (median of the "
                         "per-pair ratios).  The <2%% bar is certified by "
                         "the main pass's deterministic stamp-count x "
                         "unit-cost account; the wall-clock pairs only "
                         "corroborate it, so the default pays ONE extra "
                         "pair (2 passes, like the spec/fuse comparison "
                         "passes).  Raise it on a noisy shared box where a "
                         "single adjacent-pair ratio drifts several %%")
    ap.add_argument("--no-history", action="store_true",
                    help="do not append this run's trajectory row to "
                         "BENCH_SERVE.jsonl (the default run records one: "
                         "mode axes + key perf metrics, schema-checked and "
                         "CI-enforced by tools/check_bench.py)")
    ap.add_argument("--history", type=str, default=None,
                    help="trajectory file to append to (default: "
                         "BENCH_SERVE.jsonl next to this script)")
    ap.add_argument("--debug-bundle-dir", type=str, default="serve_debug",
                    help="where a crash or drain-invariant failure writes "
                         "the postmortem debug bundle ('' disables)")
    ap.add_argument("--trace-dir", type=str, default=None,
                    help="capture the timed section into this directory: "
                         "chrome trace of engine host phases + per-step "
                         "timeline + metrics dump (host-side only — for a "
                         "jax device capture wrap a short window in "
                         "engine.trace(dir) directly); main pass only")
    args = ap.parse_args()
    if args.request_rate is not None and args.request_rate <= 0:
        ap.error("--request-rate must be > 0")
    if args.multi_turn < 1:
        ap.error("--multi-turn must be >= 1")
    if not 0.0 <= args.session_return_frac <= 1.0:
        ap.error("--session-return-frac must be in [0, 1]")
    if args.tracing_reps < 1:
        ap.error("--tracing-reps must be >= 1")
    if args.spec_len < 0:
        ap.error("--spec-len must be >= 0")
    if args.mp < 1:
        ap.error("--mp must be >= 1")
    if args.oversubscribe < 0:
        ap.error("--oversubscribe must be >= 0")
    if args.replicas < 1:
        ap.error("--replicas must be >= 1")
    if args.prefill_chunk is not None and args.prefill_chunk != "auto":
        try:
            args.prefill_chunk = int(args.prefill_chunk)
        except ValueError:
            ap.error("--prefill-chunk must be an integer or 'auto'")
    spec_len = 0 if args.no_spec else args.spec_len
    if args.mp > 1:
        # make the CPU host expose enough virtual chips BEFORE jax initializes
        # (same trick as the multichip training dryrun); harmless on TPU
        flag = f"--xla_force_host_platform_device_count={max(args.mp, 8)}"
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = \
                (os.environ.get("XLA_FLAGS", "") + " " + flag).strip()

    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import GPTConfig

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    kw = dict(prefill_chunk=args.prefill_chunk,
              prefix_cache=not args.no_prefix_cache,
              shared_prefix_frac=args.shared_prefix_frac,
              oversubscribe=args.oversubscribe, preempt=args.preempt,
              mp=args.mp,
              kv_tier=not args.no_kv_tier, spill_dir=args.spill_dir,
              multi_turn=args.multi_turn,
              session_return_frac=args.session_return_frac,
              request_tracing=not args.no_request_tracing,
              debug_bundle_dir=args.debug_bundle_dir)
    if on_tpu:
        config = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                           num_heads=16, max_seq_len=2048, dtype=jnp.bfloat16)
        kw.update(config=config, num_requests=64, num_slots=32, page_size=16,
                  max_model_len=1024, max_new_tokens=64,
                  request_rate=16.0 if args.request_rate is None
                  else args.request_rate)
        metric = "serve_decode_tokens_per_sec_per_chip"
    else:  # CI smoke: tiny config, same scheduler/paging code paths
        kw.update(num_requests=32, num_slots=4, page_size=8, max_model_len=64,
                  max_new_tokens=6,
                  request_rate=float("inf") if args.request_rate is None
                  else args.request_rate)
        metric = "serve_decode_tokens_per_sec (cpu smoke)"
    fuse = not args.no_fuse
    quant = dict(weight_dtype=args.weight_dtype, kv_dtype=args.kv_dtype)
    stats = run_serve_bench(spec_len=spec_len, fuse=fuse,
                            trace_dir=args.trace_dir, **quant, **kw)
    if args.weight_dtype == "int8" or args.kv_dtype == "int8":
        # fp comparison on the SAME stream: the quantized pass's capacity
        # win (kv_pool_bytes, preemptions/step at the same byte budget) and
        # its accuracy price (top-1 token agreement — weight-only int8 +
        # int8 KV is a lossy approximation, so the bar is a rate, not the
        # byte parity every fp A/B in this bench holds itself to)
        base = run_serve_bench(spec_len=spec_len, fuse=fuse, **kw)
        total = agree = 0
        for qt, ft in zip(stats["output_tokens"], base["output_tokens"]):
            total += max(len(qt), len(ft))
            agree += sum(int(a == b) for a, b in zip(qt, ft))
        stats["fp_kv_pool_bytes"] = base["kv_pool_bytes"]
        stats["kv_pool_bytes_ratio"] = round(
            base["kv_pool_bytes"] / max(stats["kv_pool_bytes"], 1), 3)
        stats["fp_goodput_tokens_per_sec"] = base["goodput_tokens_per_sec"]
        stats["fp_preemptions_per_step"] = base["preemptions_per_step"]
        stats["preemptions_per_step_delta"] = round(
            stats["preemptions_per_step"] - base["preemptions_per_step"], 4)
        stats["top1_agreement"] = round(agree / max(total, 1), 4)
    if args.multi_turn > 1 and not args.no_kv_tier:
        # tier on/off A/B on the SAME multi-turn stream: restores are
        # bit-exact KV, so greedy outputs must match byte-for-byte
        # (kv_tier_parity — session-keyed digest), and the capacity win is
        # the returning-turn prefill the tier made unnecessary
        # (returning_prefilled_drop) plus the TTFT a returning session no
        # longer spends re-prefilling its conversation
        base = run_serve_bench(spec_len=spec_len, fuse=fuse, **quant,
                               **dict(kw, kv_tier=False))
        stats["no_tier_prefilled_tokens"] = base["prefilled_tokens"]
        stats["no_tier_returning_prefilled_tokens"] = \
            base["returning_prefilled_tokens"]
        stats["returning_prefilled_drop"] = round(
            1.0 - stats["returning_prefilled_tokens"] /
            max(base["returning_prefilled_tokens"], 1), 4)
        stats["no_tier_returning_ttft_p50_ms"] = \
            base["returning_ttft_p50_ms"]
        stats["no_tier_ttft_p50_ms"] = base["ttft_p50_ms"]
        stats["kv_tier_parity"] = \
            stats["outputs_digest"] == base["outputs_digest"]
    if args.oversubscribe > 0:
        # unpressured comparison on the SAME stream at F=1 (pool capacity ==
        # submitted footprint, same slot count and machinery, no pressure):
        # preemption must cost throughput, not tokens — greedy outputs
        # byte-identical, goodput_ratio the honest price of running F x
        # oversubscribed
        base = run_serve_bench(spec_len=spec_len, fuse=fuse, **quant,
                               **dict(kw, oversubscribe=1.0))
        stats["unpressured_goodput_tokens_per_sec"] = \
            base["goodput_tokens_per_sec"]
        stats["goodput_ratio"] = round(
            stats["goodput_tokens_per_sec"] /
            max(base["goodput_tokens_per_sec"], 1e-9), 3)
        stats["oversubscribe_parity"] = \
            stats["outputs_digest"] == base["outputs_digest"]
    if spec_len:
        # spec on/off delta on the SAME stream: greedy acceptance is lossless,
        # so the digests must match and the tokens/s ratio is the honest win
        # (the comparison pass inherits the main pass's tracing setting, so
        # both sides carry the same tracing cost and the ratio stays fair)
        base = run_serve_bench(spec_len=0, fuse=fuse, **quant, **kw)
        stats["no_spec_decode_tokens_per_sec_per_chip"] = \
            base["decode_tokens_per_sec_per_chip"]
        stats["spec_speedup"] = round(
            stats["decode_tokens_per_sec_per_chip"] /
            max(base["decode_tokens_per_sec_per_chip"], 1e-9), 3)
        stats["spec_parity"] = \
            stats["outputs_digest"] == base["outputs_digest"]
    if fuse:
        # fused vs three-program A/B on the SAME stream (the --no-fuse
        # escape hatch as one flag): greedy parity must be byte-exact, and
        # the dispatch win shows as dispatches_per_step 1.0 vs up to 3 plus
        # the tokens/s ratio (on TPU the dispatch overhead is the payoff; on
        # CPU the bar is "no regression")
        unfused = run_serve_bench(spec_len=spec_len, fuse=False, **quant,
                                  **kw)
        stats["no_fuse_decode_tokens_per_sec_per_chip"] = \
            unfused["decode_tokens_per_sec_per_chip"]
        stats["no_fuse_dispatches_per_step"] = \
            unfused["dispatches_per_step"]
        stats["fused_speedup"] = round(
            stats["decode_tokens_per_sec_per_chip"] /
            max(unfused["decode_tokens_per_sec_per_chip"], 1e-9), 3)
        stats["fuse_parity"] = \
            stats["outputs_digest"] == unfused["outputs_digest"]
    if not args.no_request_tracing:
        # tracing on/off A/B on the SAME stream: the always-on plane
        # (per-request timelines + metric exemplars) must cost < 2% of the
        # timed section's tokens/s and CANNOT touch tokens (instrumentation
        # never feeds the executables).  The BAR is held by the main pass's
        # deterministic account (`tracing_overhead_measured`: stamp count x
        # microbenched unit cost over the timed section — reproducible to
        # the microsecond); this wall-clock A/B corroborates it with the
        # MEDIAN OF PER-PAIR RATIOS over --tracing-reps back-to-back on/off
        # pairs (ABBA order): a shared-CPU smoke's absolute tokens/s drifts
        # ±10%+ on multi-second timescales, so comparing each pair's
        # ADJACENT runs cancels the drift that medians of the two sides
        # taken separately would inherit — but its residual noise is still
        # several %, which is WHY it corroborates rather than certifies.
        # Byte-exact parity, the half of the claim that matters most, is
        # exact in every run.  The main pass is excluded (it is the
        # process's coldest run, and under --trace-dir it carried the
        # profiler capture).
        reps = args.tracing_reps
        on_runs, off_runs = [], []
        for i in range(reps):
            sides = [True, False] if i % 2 == 0 else [False, True]
            for tracing_on in sides:
                run = run_serve_bench(
                    spec_len=spec_len, fuse=fuse, **quant,
                    **(kw if tracing_on
                       else dict(kw, request_tracing=False)))
                (on_runs if tracing_on else off_runs).append(run)

        ratio = median([on["decode_tokens_per_sec_per_chip"] /
                        max(off["decode_tokens_per_sec_per_chip"], 1e-9)
                        for on, off in zip(on_runs, off_runs)])
        stats["no_tracing_decode_tokens_per_sec_per_chip"] = median(
            [r["decode_tokens_per_sec_per_chip"] for r in off_runs])
        stats["tracing_tokens_per_sec_ratio"] = round(ratio, 3)
        stats["tracing_overhead_wall"] = round(1.0 - ratio, 4)
        # the bar number: the deterministic stamp-count x unit-cost account,
        # taken from the warm tracing-on A/B passes — the main pass's own
        # account divides by a timed section that under --trace-dir carried
        # the profiler capture, which would understate the ratio.  The noisy
        # wall ratio above corroborates but cannot certify it.
        acct = [r["tracing_overhead_measured"] for r in on_runs
                if r.get("tracing_overhead_measured") is not None]
        stats["tracing_overhead"] = (round(median(acct), 6) if acct
                                     else stats["tracing_overhead_measured"])
        stats["tracing_parity"] = all(
            r["outputs_digest"] == stats["outputs_digest"]
            for r in on_runs + off_runs)
    # dp fleet axes ride on every row (schema v3); the fleet passes
    # themselves run only when asked — run_fleet_bench replays ITS OWN
    # pre-drawn multi-turn stream through a single-engine parity oracle,
    # the requested-router fleet and the cache-blind round-robin baseline
    stats["replicas"] = args.replicas
    stats["router"] = args.router if args.replicas > 1 else None
    if args.replicas > 1:
        stats.update(run_fleet_bench(replicas=args.replicas,
                                     router=args.router))
    # disaggregated prefill/decode axes (schema v4): role split + restart
    # restore sub-pass; both null on non-disagg rows
    stats["disagg"] = args.disagg
    stats["restart"] = True if args.disagg else None
    if args.disagg:
        stats.update(run_disagg_bench(roles=args.disagg))
    # per-request streams fed the agreement score above; the digest already
    # fingerprints them, so keep the JSON line bounded
    stats.pop("output_tokens", None)
    if not args.no_history:
        # the serving trajectory: one schema-versioned row per run (mode
        # axes + key perf metrics) appended AFTER every comparison pass so
        # fused_speedup/parity land in it — tools/check_bench.py owns the
        # row shape, validates it here, and --ci enforces the declared
        # SERVE_PERF_FLOORS against a fresh run
        from tools.check_bench import DEFAULT_HISTORY, append_bench_row
        path = args.history or DEFAULT_HISTORY
        append_bench_row(stats, path=path)
        print(f"[bench_serve] trajectory row appended to {path}",
              file=sys.stderr)
    print(json.dumps({"metric": metric,
                      "value": stats["decode_tokens_per_sec_per_chip"],
                      "unit": "tokens/s/chip", **stats}))


if __name__ == "__main__":
    main()
