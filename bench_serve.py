"""Serving benchmark: continuous-batching engine throughput under a Poisson
request stream (ref vLLM benchmark_serving; Orca iteration-level scheduling).

Prints ONE JSON line: {"metric", "value", "unit", "requests", "decode_iters",
"decode_executables", "prefill_executables", "buckets"}.

TPU: GPT-3 1.3B shape at bf16, 32-slot engine, 64 mixed-length requests drawn
from a Poisson arrival process.  CPU smoke (CI tier-1): `gpt_tiny`, 32
requests, <10 s — same scheduler/paging code paths, asserting the compiled
executable bound (1 decode + <= #buckets prefill programs) that makes
continuous batching viable on TPU in the first place.
"""
from __future__ import annotations

import json
import time

import numpy as np


def run_serve_bench(config=None, *, num_requests=32, num_slots=4,
                    page_size=8, max_model_len=None, max_new_tokens=8,
                    request_rate=float("inf"), seed=0, params=None):
    """Replay a Poisson request stream through LLMEngine; returns the metrics
    dict (also the CI smoke entrypoint — tests assert on the executable
    counts).  request_rate=inf enqueues everything up front (offline batch
    throughput); a finite rate interleaves arrivals with engine steps.
    """
    import jax
    import jax.numpy as jnp

    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models import gpt as gpt_mod

    if config is None:
        config = gpt_mod.gpt_tiny(128)
    if params is None:
        params = gpt_mod.init_params(config, jax.random.key(seed))
    max_model_len = max_model_len or config.max_seq_len

    eng = LLMEngine(params, config, num_slots=num_slots, page_size=page_size,
                    max_model_len=max_model_len)
    rng = np.random.RandomState(seed)
    max_prompt = max_model_len - max_new_tokens
    lens = rng.randint(1, max_prompt + 1, size=num_requests)
    prompts = [rng.randint(0, config.vocab_size, (n,)).astype(np.int32)
               for n in lens]
    # Poisson process: exponential inter-arrival gaps at `request_rate` req/s
    gaps = (rng.exponential(1.0 / request_rate, size=num_requests)
            if np.isfinite(request_rate) else np.zeros(num_requests))
    arrivals = np.cumsum(gaps)

    # warmup: compile the decode executable + every REACHABLE prefill bucket
    # once so the timed section measures steady-state serving, not compilation
    # (a bucket past max_prompt is still reachable by shorter prompts, so warm
    # it with the longest admissible prompt that maps to it)
    for n in sorted({min(b, max_prompt) for b in eng.buckets}):
        eng.add_request(np.zeros((n,), np.int32), max_new_tokens=1)
    eng.run()

    t0 = time.perf_counter()
    pending = list(zip(arrivals, prompts))
    done = 0
    while pending or eng.has_work:
        now = time.perf_counter() - t0
        while pending and pending[0][0] <= now:
            _, p = pending.pop(0)
            eng.add_request(p, max_new_tokens=max_new_tokens)
        if eng.has_work:
            done += len(eng.step())
        elif pending:
            time.sleep(min(pending[0][0] - now, 0.01))
    dt = time.perf_counter() - t0
    assert done == num_requests, (done, num_requests)

    st = eng.stats()
    # ACTIVE decode tokens only — idle slots in ramp-up/drain iterations are
    # not useful work and would overstate throughput at low arrival rates
    decode_tokens = st["decode_tokens"]
    n_chips = max(1, len(jax.devices()))
    return {
        "decode_tokens_per_sec_per_chip": round(decode_tokens / dt / n_chips, 1),
        "generated_tokens_per_sec": round(num_requests * max_new_tokens / dt, 1),
        "requests": num_requests,
        "elapsed_s": round(dt, 3),
        "decode_iters": st["decode_iterations"],
        "decode_executables": st["decode_executables"],
        "prefill_executables": st["prefill_executables"],
        "buckets": st["buckets"],
        "kv_token_capacity": st["kv_token_capacity"],
        "dense_token_footprint": st["dense_token_footprint"],
    }


def main():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import GPTConfig

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if on_tpu:
        config = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                           num_heads=16, max_seq_len=2048, dtype=jnp.bfloat16)
        stats = run_serve_bench(config, num_requests=64, num_slots=32,
                                page_size=16, max_model_len=1024,
                                max_new_tokens=64, request_rate=16.0)
        metric = "serve_decode_tokens_per_sec_per_chip"
    else:  # CI smoke: tiny config, same scheduler/paging code paths
        stats = run_serve_bench(num_requests=32, num_slots=4, page_size=8,
                                max_model_len=64, max_new_tokens=6)
        metric = "serve_decode_tokens_per_sec (cpu smoke)"
    print(json.dumps({"metric": metric,
                      "value": stats["decode_tokens_per_sec_per_chip"],
                      "unit": "tokens/s/chip", **stats}))


if __name__ == "__main__":
    main()
