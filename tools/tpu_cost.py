#!/usr/bin/env python
"""tpu_cost: static HBM/collective/roofline accounting over the serving
executables, with CI-enforced resource budgets.

The serving jaxprs are traced (no dispatch) and compiled (no execution) and
four accounts are read off them (`paddle_tpu/analysis/cost_model.py`):

- **at-rest HBM** per device: param bytes split sharded-vs-replicated via
  the mp serving layout, plus the KVH-sharded page-pool bytes.  JXP006
  flags any replicated buffer above the declared ceiling — the
  embedding/head replication that blocks 70B-class configs.
- **peak transient HBM**: donation-aware per-eqn liveness over each
  program's jaxpr (the donated pool aliases out and allocates nothing).
  JXP008 flags a program over its declared peak budget.  XLA's own
  `memory_analysis()` temp bytes print alongside for calibration.
- **collectives**: psum/all-gather/reduce-scatter/collective-permute
  traffic read from the OPTIMIZED HLO (GSPMD inserts them after tracing),
  payload bytes x while-loop trip counts (the layer scan).  JXP007 flags
  undeclared or over-budget collective bytes/step; mp1 programs must be
  collective-free.
- **roofline**: analytic flops + compulsory HBM traffic over nameplate
  device specs -> a predicted step time per executable (`bench_serve.py`
  emits the same model's `predicted_step_ms` next to measured time).

Budgets are declared ONCE in `paddle_tpu/analysis/registry.py::
SERVE_RESOURCE_BUDGET`, next to the program-count budget — one declaration,
one yardstick for the quantized-KV and 70B-head roadmap arcs.

Usage:
  JAX_PLATFORMS=cpu python tools/tpu_cost.py          # human report, mp1/2/4
  JAX_PLATFORMS=cpu python tools/tpu_cost.py --ci     # enforce budgets (CI)
  python tools/tpu_cost.py --json                     # machine-readable
  python tools/tpu_cost.py --no-mp                    # single-device hosts
  python tools/tpu_cost.py --replicated-ceiling N     # override (testing)
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the mp pass needs virtual chips; must land before jax initializes
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")


def _fmt_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):.2f} MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f} KiB"
    return f"{n} B"


def _print_report(reports) -> None:
    for mp, rep in sorted(reports.items()):
        ar = rep["at_rest"]
        print(f"== mp={mp} — at-rest HBM per device "
              f"({_fmt_bytes(ar['per_device_bytes'])})")
        print(f"   params sharded   "
              f"{_fmt_bytes(ar['param_bytes_sharded_per_device'])}"
              f"  (global {_fmt_bytes(ar['param_bytes_sharded'])})")
        print(f"   params replicated {_fmt_bytes(ar['param_bytes_replicated'])}"
              f"  (top: " + ", ".join(
                  f"{b['name']}={_fmt_bytes(b['bytes'])}"
                  for b in ar["top_replicated"][:2]) + ")")
        print(f"   page pool        "
              f"{_fmt_bytes(ar['pool_bytes_per_device'])}"
              f"  (global {_fmt_bytes(ar['pool_bytes'])})")
        qr = rep.get("at_rest_quantized")
        if qr is not None:
            print(f"   int8 engine      pool "
                  f"{_fmt_bytes(qr['pool_bytes'])} "
                  f"({rep['quantized_pool_ratio']}x smaller), replicated "
                  f"params {_fmt_bytes(qr['param_bytes_replicated'])} (fp "
                  f"{_fmt_bytes(ar['param_bytes_replicated'])}), host-pool "
                  f"bound {_fmt_bytes(rep['host_pool_bytes_int8'])}")
        print(f"   {'program':28s} {'flops':>10s} {'peak HBM':>10s} "
              f"{'xla temp':>10s} {'coll B/step':>11s} {'pred ms':>8s}")
        for p in rep["programs"]:
            xla = p.get("xla_temp_bytes")
            print(f"   {p['name']:28s} {p['flops']:>10d} "
                  f"{_fmt_bytes(p['peak_bytes']):>10s} "
                  f"{(_fmt_bytes(xla) if xla is not None else '-'):>10s} "
                  f"{p.get('collective_bytes_per_step', 0):>11d} "
                  f"{p['predicted_ms']:>8.4f}")


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_cost", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--ci", action="store_true",
                    help="CI mode (recipe symmetry with tpu_lint --ci); any "
                         "JXP006/JXP007/JXP008 finding exits nonzero with or "
                         "without this flag")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object with the full account")
    ap.add_argument("--no-mp", action="store_true",
                    help="skip the mp=2/mp=4 passes (single-device hosts)")
    ap.add_argument("--replicated-ceiling", type=int, default=None,
                    help="override the declared replicated-bytes ceiling "
                         "(budget-injection hook for tests)")
    ap.add_argument("--peak-budget", type=int, default=None,
                    help="override EVERY executable's peak-HBM budget with "
                         "one value (budget-injection hook for tests)")
    args = ap.parse_args()

    from paddle_tpu.analysis import registry
    from paddle_tpu.analysis.cost_model import device_spec, run_cost_checks

    budget = dict(registry.SERVE_RESOURCE_BUDGET)
    if args.replicated_ceiling is not None:
        budget["replicated_bytes_ceiling"] = args.replicated_ceiling
    if args.peak_budget is not None:
        budget["peak_hbm_bytes"] = {
            k: args.peak_budget for k in budget.get("peak_hbm_bytes", {})}
    reports, findings = run_cost_checks(include_mp=not args.no_mp,
                                        budget=budget)
    spec = device_spec()

    if args.json:
        print(json.dumps({
            "tool": "tpu_cost", "ok": not findings,
            "device_spec": spec.name,
            "reports": {f"mp{m}": rep for m, rep in reports.items()},
            "findings": [f.to_json() for f in findings],
        }))
    else:
        _print_report(reports)
        for f in findings:
            print(f.format())
        print(f"tpu_cost: {len(findings)} finding(s) against "
              f"SERVE_RESOURCE_BUDGET", file=sys.stderr)
    # same convention as tpu_lint: findings fail the run in EVERY mode — a
    # human-report invocation must not mask a budget regression with exit 0
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
