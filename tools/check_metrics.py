#!/usr/bin/env python
"""CI guard: the serving observability schema.

The metrics surface is an API: dashboards scrape `to_prometheus()`, benches
read `stats()`, and the ROADMAP's preemption/router work will consume the
page gauges and step timeline.  This script re-measures the contract on every
run so a future PR cannot silently drop a key, break the exposition format,
or make a "counter" go backwards:

- **stats() schema** — every key in REQUIRED_STATS_KEYS present (the frozen
  serving-stats surface, including the latency histogram block);
- **registry schema** — required counters/gauges/histograms present in
  `metrics.snapshot()`;
- **exposition** — `to_prometheus()` parses line-by-line against the
  Prometheus text format: HELP/TYPE comments only, well-formed samples,
  `_bucket` series cumulative and ending at `+Inf` == `_count`;
- **monotonicity** — across a CPU-smoke engine loop that exercises admission,
  chunked prefill, speculative verify, prefix hits, LRU eviction AND abort,
  no counter ever decreases between steps;
- **program budget** — decode-side compiled programs within the budget
  declared in paddle_tpu/analysis/registry.py with metrics enabled
  (observability is host-only; see tools/check_program_count.py for the
  full per-mesh budget).

Exits non-zero with a diff on violation.  Usage:
    JAX_PLATFORMS=cpu python tools/check_metrics.py
"""
from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED_STATS_KEYS = frozenset({
    "decode_executables", "verify_executables", "prefill_executables",
    "copy_executables", "swap_executables", "buckets", "prefill_chunk",
    "spec_len", "mp",
    "engine_steps", "decode_iterations", "decode_tokens", "verify_steps",
    "spec_events", "spec_drafted_tokens", "spec_accepted_tokens",
    "spec_emitted_tokens", "spec_backoffs", "accepted_per_step",
    "prefill_chunks", "prefilled_tokens", "prefix_cached_tokens",
    "prefix_hit_requests", "prefix_hit_rate", "cow_page_copies",
    "pages_in_use", "pages_free", "pages_evictable", "prefix_evictions",
    "kv_token_capacity", "dense_token_footprint", "queued", "prefilling",
    "running", "finished_requests", "aborted_requests", "latency",
    # overload surface (oversubscription PR): admission/preempt modes + the
    # preemption/swap/deadline counters the bench and dashboards consume
    "admission", "preempt", "preemptions", "preempt_swaps",
    "preempt_recomputes", "swapped_pages", "swap_ms", "recomputed_tokens",
    "timeouts", "rejected_requests", "swapped", "kv_pages_swapped",
    "kv_pool_pressure",
    # quantized serving (ISSUE 11): the quantization knobs, the at-rest pool
    # bytes the capacity math keys on, and the swap-pool intake gate counter
    "weight_dtype", "kv_dtype", "kv_pool_bytes", "intake_swap_rejects",
})
REQUIRED_LATENCY_KEYS = frozenset(
    {"queue_s", "ttft_s", "tpot_s", "e2e_s", "step_s"})
REQUIRED_COUNTERS = frozenset({
    "decode_iterations", "decode_tokens", "prefill_chunks",
    "prefilled_tokens", "prefix_cached_tokens", "prefix_hit_requests",
    "cow_page_copies", "verify_steps", "spec_events", "spec_drafted_tokens",
    "spec_accepted_tokens", "spec_emitted_tokens", "spec_backoffs",
    "finished_requests", "aborted_requests", "prefix_evictions",
    "preemptions", "preempt_swaps", "preempt_recomputes", "swapped_pages",
    "swap_ms", "recomputed_tokens", "timeouts", "rejected_requests",
    "intake_swap_rejects",
})
REQUIRED_GAUGES = frozenset({
    "queued", "prefilling", "running", "kv_pages_in_use", "kv_pages_free",
    "kv_pages_evictable", "prefix_cached_pages", "kv_pages_swapped",
    "kv_pool_pressure", "kv_pool_bytes",
})
REQUIRED_HISTOGRAMS = frozenset({
    "queue_time_seconds", "ttft_seconds", "tpot_seconds",
    "e2e_latency_seconds", "step_seconds",
})

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"              # metric name
    r'(\{le="[^"]+"\})?'                        # optional le label (hist)
    r" (-?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|Inf|NaN)|\+Inf)$")
_COMMENT = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")


def parse_prometheus(text):
    """Minimal exposition-format checker: returns {name: [(labels, value)]},
    raising ValueError on any malformed line."""
    samples = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            if not _COMMENT.match(line):
                raise ValueError(f"malformed comment line: {line!r}")
            continue
        m = _SAMPLE.match(line)
        if m is None:
            raise ValueError(f"malformed sample line: {line!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        samples.setdefault(name, []).append((labels, float(value)))
    return samples


def check_exposition(text, errors):
    try:
        samples = parse_prometheus(text)
    except ValueError as e:
        errors.append(str(e))
        return
    for base in (n[:-len("_bucket")] for n in samples if n.endswith("_bucket")):
        buckets = samples[base + "_bucket"]
        counts = [v for _, v in buckets]
        if counts != sorted(counts):
            errors.append(f"{base}_bucket series is not cumulative: {counts}")
        if buckets[-1][0] != '{le="+Inf"}':
            errors.append(f"{base}_bucket does not end at le=+Inf")
        count = samples.get(base + "_count")
        if count is None:
            errors.append(f"{base}_count sample missing")
        elif count[0][1] != counts[-1]:
            errors.append(f"{base}: +Inf bucket {counts[-1]} != "
                          f"_count {count[0][1]}")
        if base + "_sum" not in samples:
            errors.append(f"{base}_sum sample missing")


def run_smoke(errors):
    """Drive every scheduler lane on a tiny engine, asserting per-step that
    no counter decreases; returns the final stats()/snapshot pair."""
    import jax
    import numpy as np

    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models import gpt as G

    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    # 8-page pool under 2 slots: retiring requests park prefixes in the LRU
    # and later distinct prompts evict them (the eviction counter must move)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, num_pages=9,
                    max_model_len=64, prefill_chunk=16, spec_len=3, seed=11)
    rng = np.random.RandomState(11)
    shared = rng.randint(0, cfg.vocab_size, (20,)).astype(np.int32)
    rids = []
    for i in range(10):
        if i % 3 == 0:      # shared-prefix family: prefix hits + COW
            tail = rng.randint(0, cfg.vocab_size, (i,)).astype(np.int32)
            prompt = np.concatenate([shared, tail]) if i else shared.copy()
        else:               # distinct prompts: forces LRU eviction churn
            prompt = rng.randint(0, cfg.vocab_size,
                                 (int(rng.randint(4, 40)),)).astype(np.int32)
        rids.append(eng.add_request(prompt, max_new_tokens=6))
    prev = eng.metrics.snapshot()["counters"]
    aborted = False
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        if steps == 4 and not aborted:      # mid-flight abort lane
            aborted = eng.abort(rids[-1])
        cur = eng.metrics.snapshot()["counters"]
        for k, v in cur.items():
            if v < prev.get(k, 0):
                errors.append(f"counter {k} decreased: "
                              f"{prev[k]} -> {v} at step {steps}")
        prev = cur
    if not aborted:
        errors.append("abort lane never exercised")
    st = eng.stats()
    if st["prefix_evictions"] < 1:
        errors.append("eviction lane never exercised "
                      f"(prefix_evictions={st['prefix_evictions']})")
    if st["spec_events"] < 1:
        errors.append("speculative lane never exercised (spec_events=0)")
    if st["prefix_hit_requests"] < 1:
        errors.append("prefix-hit lane never exercised")
    return eng, st


def main() -> int:
    errors = []
    eng, st = run_smoke(errors)

    missing = REQUIRED_STATS_KEYS - set(st)
    if missing:
        errors.append(f"stats() missing keys: {sorted(missing)}")
    if not missing:
        lat_missing = REQUIRED_LATENCY_KEYS - set(st["latency"])
        if lat_missing:
            errors.append(f"stats()['latency'] missing: {sorted(lat_missing)}")

    snap = eng.metrics.snapshot()
    for section, required in (("counters", REQUIRED_COUNTERS),
                              ("gauges", REQUIRED_GAUGES),
                              ("histograms", REQUIRED_HISTOGRAMS)):
        miss = required - set(snap.get(section, {}))
        if miss:
            errors.append(f"snapshot()[{section!r}] missing: {sorted(miss)}")
    try:
        json.dumps(snap)
    except TypeError as e:
        errors.append(f"snapshot() is not JSON-serializable: {e}")

    check_exposition(eng.metrics.to_prometheus(), errors)

    # observability must be free of compiled programs: decode-side budget
    # unchanged — the bound comes from the registry (declared ONCE) so this
    # guard cannot drift from check_program_count's
    from paddle_tpu.analysis.registry import SERVE_PROGRAM_BUDGET
    bound = SERVE_PROGRAM_BUDGET["decode_side_executables"]
    decode_side = st["decode_executables"] + st["verify_executables"]
    if decode_side > bound:
        errors.append(f"decode-side executables {decode_side} > {bound} with "
                      f"metrics enabled — instrumentation leaked into a "
                      f"compiled program")

    report = {"metric": "serve_metrics_schema", "ok": not errors,
              "decode_side_executables": decode_side,
              "prefix_evictions": st["prefix_evictions"],
              "spec_events": st["spec_events"],
              "aborted_requests": st["aborted_requests"],
              "errors": errors}
    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    print(json.dumps(report))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
