#!/usr/bin/env python
"""CI guard: the serving observability schema.

The metrics surface is an API: dashboards scrape `to_prometheus()`, benches
read `stats()`, and the ROADMAP's preemption/router work will consume the
page gauges and step timeline.  This script re-measures the contract on every
run so a future PR cannot silently drop a key, break the exposition format,
or make a "counter" go backwards:

- **stats() schema** — every key in REQUIRED_STATS_KEYS present (the frozen
  serving-stats surface, including the latency histogram block and the SLO
  block: deadline attainment + per-priority goodput);
- **registry schema** — required counters/gauges/histograms present in
  `metrics.snapshot()`;
- **exposition** — `to_prometheus()` parses line-by-line against the
  Prometheus text format: HELP/TYPE comments only, well-formed samples
  (general label sets accepted), `_bucket` series cumulative and ending at
  `+Inf` == `_count`, and OpenMetrics `# {...} value` exemplars syntactically
  valid with the exemplar value inside its bucket's `le` bound;
- **exemplar round-trip** — the smoke engine's exposition carries >= 1
  exemplar whose `request_id` resolves through
  `engine.export_request_trace()` to a non-empty chrome-trace span tree (the
  p99-to-request lookup the tracing layer exists for);
- **merged-registry schema** — `MetricsRegistry.merge()` counter/histogram
  math against hand-computed goldens, and a two-member `FleetMetrics`
  exposition that parses with per-engine labels plus `llm_fleet_*` totals
  equal to the member sums;
- **obs-server smoke** — `ObservabilityServer` over the live smoke engine on
  an ephemeral loopback port: /metrics parses under this same checker,
  /stats carries the required keys, /requests/<rid> serves the exemplar's
  span tree, /debug is valid JSON with the bundle schema;
- **health & signals schema** — `stats()` carries the windowed-rate block
  (every family over every window), a folded `health` state from the known
  set with burn rates, and the complete `roofline` account; the exposition
  carries the rate/burn/health/roofline gauge families; `/healthz` serves
  the REAL health evaluation (structured state + per-signal detail, 200 for
  ok/degraded, 503 for overloaded — never the old hardcoded stub); and the
  `engine_health` gauge fleet-merges WORST-OF (max), not sum;
- **front-door smoke** — the serving front door (`inference.frontend
  .ServingFrontend`) over a 2-replica dp `EngineFleet` on a real loopback
  socket: the obs routes served THROUGH the door (one server, `/v1/*` next
  to `/metrics`) carry the fleet exposition — per-``{engine=...}`` series
  for every replica plus `llm_fleet_*` merged totals equal to the member
  sums — `/stats` is the per-label map, `/healthz` is the worst-of fleet
  rollup (503 the moment any member reads overloaded), and the 404 route
  list advertises the inference endpoints;
- **disagg smoke** — a 1P:1D role fleet over the durable tier store: the
  `kv_handoff_*` counters move on the prefill replica and `kv_tier_restores`
  on the decode replica, the prefill request's timeline carries the
  `handoff` event, and `/healthz` served through the front door labels every
  per-engine entry with its role;
- **monotonicity** — across a CPU-smoke engine loop that exercises admission,
  chunked prefill, speculative verify, prefix hits, LRU eviction AND abort,
  no counter ever decreases between steps;
- **program budget** — decode-side compiled programs within the budget
  declared in paddle_tpu/analysis/registry.py with metrics enabled
  (observability — tracing and exemplars included — is host-only; see
  tools/check_program_count.py for the full per-mesh budget).

Exits non-zero with a diff on violation.  Usage:
    JAX_PLATFORMS=cpu python tools/check_metrics.py
"""
from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED_STATS_KEYS = frozenset({
    "decode_executables", "verify_executables", "prefill_executables",
    "copy_executables", "swap_executables", "buckets", "prefill_chunk",
    "spec_len", "mp",
    "engine_steps", "decode_iterations", "decode_tokens", "verify_steps",
    "spec_events", "spec_drafted_tokens", "spec_accepted_tokens",
    "spec_emitted_tokens", "spec_backoffs", "accepted_per_step",
    "prefill_chunks", "prefilled_tokens", "prefix_cached_tokens",
    "prefix_hit_requests", "prefix_hit_rate", "cow_page_copies",
    "pages_in_use", "pages_free", "pages_evictable", "prefix_evictions",
    "kv_token_capacity", "dense_token_footprint", "queued", "prefilling",
    "running", "finished_requests", "aborted_requests", "latency",
    # overload surface (oversubscription PR): admission/preempt modes + the
    # preemption/swap/deadline counters the bench and dashboards consume
    "admission", "preempt", "preemptions", "preempt_swaps",
    "preempt_recomputes", "swapped_pages", "swap_ms", "recomputed_tokens",
    "timeouts", "rejected_requests", "swapped", "kv_pages_swapped",
    "kv_pool_pressure",
    # quantized serving (ISSUE 11): the quantization knobs, the at-rest pool
    # bytes the capacity math keys on, and the swap-pool intake gate counter
    "weight_dtype", "kv_dtype", "kv_pool_bytes", "intake_swap_rejects",
    # observability-plane PR (ISSUE 12): the SLO block (deadline attainment
    # + per-priority-class goodput) the router's SLO layer consumes
    "slo",
    # health & signals PR (ISSUE 13): windowed rates, the folded health
    # state, and the live roofline (predicted/measured/drift/anomalies)
    "rates", "health", "roofline",
    # KV tiering PR (ISSUE 15): per-tier occupancy + spill/restore traffic
    # + the rolling-hash partial-index hit counter
    "kv_tier",
})
REQUIRED_KV_TIER_KEYS = frozenset({
    "enabled", "spill_dir", "pages_host", "pages_disk", "spills",
    "restores", "restored_tokens", "partial_page_hits", "disk_spills",
    "disk_restores", "tier_drops",
    # disaggregated serving PR (ISSUE 17): the durable store + cross-engine
    # handoff surface
    "store", "handoff_exports", "handoff_pages", "handoff_tokens",
    "store_nodes_restored",
})
REQUIRED_SLO_KEYS = frozenset({
    "deadline_requests", "deadline_met", "deadline_attainment",
    "goodput_tokens_by_priority",
})
# stats()["rates"] families x window labels (inference.metrics.RATE_WINDOWS);
# each (family, window) pair is ALSO a pull gauge in the exposition
RATE_FAMILIES = ("tokens_per_sec", "admits_per_sec", "preemptions_per_sec",
                 "timeouts_per_sec", "rejects_per_sec")
RATE_WINDOW_LABELS = ("10s", "1m", "5m")
REQUIRED_HEALTH_KEYS = frozenset({"state", "code", "reasons", "burn_rates"})
REQUIRED_ROOFLINE_KEYS = frozenset({
    "predicted_step_ms", "measured_step_ms", "drift", "drift_alerts",
    "steady_state_recompiles",
})
HEALTH_STATES = ("ok", "degraded", "overloaded")
REQUIRED_LATENCY_KEYS = frozenset(
    {"queue_s", "ttft_s", "tpot_s", "e2e_s", "step_s"})
REQUIRED_COUNTERS = frozenset({
    "decode_iterations", "decode_tokens", "prefill_chunks",
    "prefilled_tokens", "prefix_cached_tokens", "prefix_hit_requests",
    "cow_page_copies", "verify_steps", "spec_events", "spec_drafted_tokens",
    "spec_accepted_tokens", "spec_emitted_tokens", "spec_backoffs",
    "finished_requests", "aborted_requests", "prefix_evictions",
    "preemptions", "preempt_swaps", "preempt_recomputes", "swapped_pages",
    "swap_ms", "recomputed_tokens", "timeouts", "rejected_requests",
    "intake_swap_rejects", "deadline_requests", "deadline_met",
    # health & signals PR: admission-rate numerator + anomaly counters
    "admitted_requests", "roofline_drift_alerts", "steady_state_recompiles",
    # KV tiering PR: spill/restore traffic + rolling-hash partial hits
    "kv_tier_spills", "kv_tier_restores", "kv_tier_restored_tokens",
    "partial_page_hits",
    # disaggregated serving PR: prefill->decode handoffs through the store
    "kv_handoff_exports", "kv_handoff_pages", "kv_handoff_tokens",
})
REQUIRED_DEBUG_BUNDLE_KEYS = frozenset({
    "version", "t", "engine", "pool", "requests", "step_trace", "stats",
    "metrics",
})
REQUIRED_GAUGES = frozenset({
    "queued", "prefilling", "running", "kv_pages_in_use", "kv_pages_free",
    "kv_pages_evictable", "prefix_cached_pages", "kv_pages_swapped",
    "kv_pool_pressure", "kv_pool_bytes",
    # health & signals PR: the folded health code (worst-of fleet merge),
    # the live roofline pair, and the SLO burn-rate pair
    "engine_health", "measured_step_ms", "roofline_drift",
    "slo_burn_rate_1m", "slo_burn_rate_5m",
    # KV tiering PR: per-tier-level occupancy
    "kv_tier_pages_host", "kv_tier_pages_disk",
}) | frozenset(
    # windowed-rate pull gauges: one per (family, window)
    f"{fam}_{w}" for fam in RATE_FAMILIES for w in RATE_WINDOW_LABELS)
REQUIRED_HISTOGRAMS = frozenset({
    "queue_time_seconds", "ttft_seconds", "tpot_seconds",
    "e2e_latency_seconds", "step_seconds",
})

# general Prometheus label set: {k="v",...} with escaped quotes/backslashes
_LABELSET = r'\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"' \
            r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*)?\}'
_NUM = r"(?:-?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|Inf|NaN)|\+Inf)"
_SAMPLE = re.compile(
    rf"^([a-zA-Z_:][a-zA-Z0-9_:]*)"             # metric name
    rf"({_LABELSET})?"                          # optional label set
    rf" ({_NUM})"                               # sample value
    rf"(?: # ({_LABELSET}) ({_NUM})(?: ({_NUM}))?)?$")  # OpenMetrics exemplar
_COMMENT = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")
_LABEL_ITEM = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_labels(labelset):
    """`{k="v",...}` (or ""/None) -> dict, unescaping values.  Unescaping is
    a single left-to-right pass (each backslash consumes exactly the next
    char) — sequential .replace calls would mis-decode a literal backslash
    followed by 'n' or a quote."""
    out = {}
    for k, v in _LABEL_ITEM.findall(labelset or ""):
        out[k] = re.sub(r"\\(.)",
                        lambda m: "\n" if m.group(1) == "n" else m.group(1),
                        v)
    return out


def series_key(labelset):
    """Grouping key for a sample's label set with the `le` bucket label
    removed: PARSED and re-serialized sorted, not regex-stripped — a label
    KEY that merely ends in "le" (``module=...``) must survive, and bucket
    rows must key identically to their `_count`/`_sum` rows regardless of
    label order."""
    items = sorted((k, v) for k, v in parse_labels(labelset).items()
                   if k != "le")
    return "{%s}" % ",".join(f'{k}="{v}"' for k, v in items) if items else ""


def parse_prometheus_full(text):
    """Exposition parser: returns `(samples, exemplars)` where samples is
    {name: [(labels, value)]} and exemplars is {(name, labels): (exemplar
    label dict, exemplar value)} for every sample carrying an OpenMetrics
    `# {...} value [ts]` exemplar suffix.  Raises ValueError on any
    malformed line — including a malformed exemplar, which the pre-exemplar
    parser would have rejected wholesale and a naive split would ignore."""
    samples = {}
    exemplars = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line == "# EOF":        # OpenMetrics terminator (obs server)
            continue
        if line.startswith("#") and not line.startswith("# {"):
            if not _COMMENT.match(line):
                raise ValueError(f"malformed comment line: {line!r}")
            continue
        m = _SAMPLE.match(line)
        if m is None:
            raise ValueError(f"malformed sample line: {line!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        samples.setdefault(name, []).append((labels, float(value)))
        if m.group(4) is not None:
            if not (name.endswith("_bucket") or name.endswith("_total")):
                raise ValueError(
                    f"exemplar on a non-bucket/counter sample: {line!r}")
            exemplars[(name, labels)] = (parse_labels(m.group(4)),
                                         float(m.group(5)))
    return samples, exemplars


def parse_prometheus(text):
    """Minimal exposition-format checker: returns {name: [(labels, value)]},
    raising ValueError on any malformed line (exemplar-tolerant; use
    parse_prometheus_full to read the exemplars too)."""
    return parse_prometheus_full(text)[0]


def check_exposition(text, errors):
    try:
        samples, exemplars = parse_prometheus_full(text)
    except ValueError as e:
        errors.append(str(e))
        return
    for base in (n[:-len("_bucket")] for n in samples if n.endswith("_bucket")):
        buckets = samples[base + "_bucket"]
        # fleet expositions carry one series per {engine=...} label set:
        # cumulative/+Inf/_count checks apply per series, keyed on the
        # labels with `le` stripped
        series = {}
        for labels, v in buckets:
            series.setdefault(series_key(labels), []).append((labels, v))
        for key, rows in series.items():
            counts = [v for _, v in rows]
            tag = f"{base}_bucket{key or ''}"
            if counts != sorted(counts):
                errors.append(f"{tag} series is not cumulative: {counts}")
            if 'le="+Inf"' not in rows[-1][0]:
                errors.append(f"{tag} does not end at le=+Inf")
            count = [v for lbl, v in samples.get(base + "_count", ())
                     if series_key(lbl) == key]
            if not count:
                errors.append(f"{base}_count sample missing for {key or '{}'}")
            elif count[0] != counts[-1]:
                errors.append(f"{tag}: +Inf bucket {counts[-1]} != "
                              f"_count {count[0]}")
        if base + "_sum" not in samples:
            errors.append(f"{base}_sum sample missing")
    # exemplar semantics: a bucket's exemplar value must sit within its le
    # bound (our histograms attach the exemplar to the bucket the value
    # landed in, so a violation means attachment or emission broke)
    for (name, labels), (ex_labels, ex_value) in exemplars.items():
        if not name.endswith("_bucket"):
            continue
        le = parse_labels(labels).get("le")
        if le is None:
            errors.append(f"exemplar on a bucket without le: {name}{labels}")
            continue
        bound = float("inf") if le == "+Inf" else float(le)
        if ex_value > bound:
            errors.append(f"exemplar value {ex_value} above its bucket "
                          f'bound le="{le}" on {name}{labels}')


def run_smoke(errors):
    """Drive every scheduler lane on a tiny engine, asserting per-step that
    no counter decreases; returns the final stats()/snapshot pair."""
    import jax
    import numpy as np

    from paddle_tpu.inference.engine import LLMEngine
    from paddle_tpu.models import gpt as G

    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(0))
    # 8-page pool under 2 slots: retiring requests park prefixes in the LRU
    # and later distinct prompts evict them (the eviction counter must move)
    # swap_pool_pages sized up so LRU-evicted prefixes SPILL to the host
    # tier (default-on tiering) instead of churning out of the budget —
    # the re-request below then restores from the tier (the restore lane)
    eng = LLMEngine(params, cfg, num_slots=2, page_size=8, num_pages=9,
                    max_model_len=64, prefill_chunk=16, spec_len=3, seed=11,
                    swap_pool_pages=64)
    rng = np.random.RandomState(11)
    shared = rng.randint(0, cfg.vocab_size, (20,)).astype(np.int32)
    rids = []
    for i in range(10):
        if i % 3 == 0:      # shared-prefix family: prefix hits + COW
            tail = rng.randint(0, cfg.vocab_size, (i,)).astype(np.int32)
            prompt = np.concatenate([shared, tail]) if i else shared.copy()
        else:               # distinct prompts: forces LRU eviction churn
            prompt = rng.randint(0, cfg.vocab_size,
                                 (int(rng.randint(4, 40)),)).astype(np.int32)
        rids.append(eng.add_request(prompt, max_new_tokens=6))
    prev = eng.metrics.snapshot()["counters"]
    aborted = False
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        if steps == 4 and not aborted:      # mid-flight abort lane
            aborted = eng.abort(rids[-1])
        cur = eng.metrics.snapshot()["counters"]
        for k, v in cur.items():
            if v < prev.get(k, 0):
                errors.append(f"counter {k} decreased: "
                              f"{prev[k]} -> {v} at step {steps}")
        prev = cur
    if not aborted:
        errors.append("abort lane never exercised")
    # tier restore lane: re-submit the shared-family prompt AFTER the
    # distinct-prompt churn evicted (= spilled) its pages — admission must
    # map the prefix from the host tier with one scatter
    eng.add_request(np.concatenate(
        [shared, rng.randint(0, cfg.vocab_size, (5,)).astype(np.int32)]),
        max_new_tokens=4)
    while eng.has_work:
        eng.step()
        cur = eng.metrics.snapshot()["counters"]
        for k, v in cur.items():
            if v < prev.get(k, 0):
                errors.append(f"counter {k} decreased: "
                              f"{prev[k]} -> {v} in the restore lane")
        prev = cur
    st = eng.stats()
    if st["prefix_evictions"] < 1:
        errors.append("eviction lane never exercised "
                      f"(prefix_evictions={st['prefix_evictions']})")
    if st["spec_events"] < 1:
        errors.append("speculative lane never exercised (spec_events=0)")
    if st["prefix_hit_requests"] < 1:
        errors.append("prefix-hit lane never exercised")
    if st["kv_tier"]["spills"] < 1:
        errors.append("KV-tier spill lane never exercised "
                      f"(kv_tier={st['kv_tier']})")
    if st["kv_tier"]["restores"] < 1:
        errors.append("KV-tier restore lane never exercised "
                      f"(kv_tier={st['kv_tier']})")
    if st["kv_tier"]["partial_page_hits"] < 1:
        errors.append("rolling-hash partial-page lane never exercised")
    return eng, st


def check_exemplar_roundtrip(eng, errors):
    """>= 1 exemplar in the live exposition, and its request_id resolves
    through export_request_trace to a non-empty chrome span tree — the
    aggregate-to-request lookup the tracing layer exists for.  Returns the
    resolved rid (for the obs-server smoke) or None."""
    try:
        _, exemplars = parse_prometheus_full(
            eng.metrics.to_prometheus(exemplars=True))
    except ValueError as e:
        errors.append(f"exposition with exemplars failed to parse: {e}")
        return None
    rids = sorted({ex[0]["request_id"] for ex in exemplars.values()
                   if "request_id" in ex[0]})
    if not rids:
        errors.append("no request_id exemplar in the smoke exposition "
                      "(request tracing defaulted off, or attachment broke)")
        return None
    rid = int(rids[0])
    tree = eng.export_request_trace(rid)
    if not (isinstance(tree, dict) and tree.get("traceEvents")):
        errors.append(f"exemplar request {rid} did not resolve to a "
                      f"chrome-trace span tree (got {type(tree).__name__})")
        return None
    names = {e.get("name") for e in tree["traceEvents"]}
    if f"request/{rid}" not in names or "enqueue" not in names:
        errors.append(f"request {rid} span tree missing root/enqueue: "
                      f"{sorted(names)}")
    return rid


def check_merge_and_fleet(eng, errors):
    """MetricsRegistry.merge math vs hand-computed goldens + a two-member
    FleetMetrics exposition (per-engine labels, llm_fleet_* totals == member
    sums) parsed under this file's own checker."""
    from paddle_tpu.inference.metrics import FleetMetrics, MetricsRegistry

    a, b = MetricsRegistry(namespace="m"), MetricsRegistry(namespace="m")
    a.counter("c").inc(3)
    b.counter("c").inc(4)
    b.counter("only_b").inc(5)                  # disjoint-name passthrough
    ha = a.histogram("h", [1.0, 2.0])
    hb = b.histogram("h", [1.0, 2.0])
    ha.observe(0.5, exemplar={"request_id": "1"})
    hb.observe(1.5)
    hb.observe(9.0)
    agg = MetricsRegistry(namespace="agg").merge(a).merge(b)
    snap = agg.snapshot()
    if snap["counters"].get("c") != 7 or snap["counters"].get("only_b") != 5:
        errors.append(f"counter merge != golden: {snap['counters']}")
    h = agg.get("h")
    if h.counts != [1, 1] or h.overflow != 1 or h.count != 3 or \
            h.sum != 11.0 or h.min != 0.5 or h.max != 9.0:
        errors.append(f"histogram merge != golden: counts={h.counts} "
                      f"overflow={h.overflow} count={h.count} sum={h.sum}")
    # fleet: the same engine twice => per-engine labels + exactly-2x totals
    fleet = FleetMetrics().add("e0", eng).add("e1", eng)
    text = fleet.to_prometheus()
    check_exposition(text, errors)
    try:
        samples = parse_prometheus(text)
    except ValueError as e:
        errors.append(f"fleet exposition failed to parse: {e}")
        return
    per = {lbl: v
           for lbl, v in samples.get("llm_engine_decode_tokens_total", ())}
    if set(per) != {'{engine="e0"}', '{engine="e1"}'}:
        errors.append(f"fleet per-engine labels wrong: {sorted(per)}")
    total = samples.get("llm_fleet_decode_tokens_total", [("", -1)])[0][1]
    if total != sum(per.values()) or total != \
            2 * eng.stats()["decode_tokens"]:
        errors.append(f"fleet merged total {total} != member sum "
                      f"{sum(per.values())}")
    # exemplar-carrying fleet text still parses, and every PER-ENGINE series
    # exemplar scopes its trace handle with ?engine= — request ids are
    # per-engine counters, so an unscoped handle is ambiguous fleet-wide
    # (the llm_fleet_* merged series keep the member's bare handle: the obs
    # server answers those with the candidate list rather than guessing)
    try:
        _, fex = parse_prometheus_full(fleet.to_prometheus(exemplars=True))
    except ValueError as e:
        errors.append(f"fleet exposition with exemplars failed to parse: {e}")
        return
    if not fex:
        errors.append("fleet exposition carries no exemplar")
    unscoped = [(name, labels) for (name, labels), ex in fex.items()
                if 'engine="' in labels and "trace" in ex[0]
                and "?engine=" not in ex[0]["trace"]]
    if unscoped:
        errors.append(f"fleet per-engine exemplar trace handles missing "
                      f"?engine= scope: {unscoped[:3]}")
    # health gauge fleet fold: a fleet with one degraded (1) and one
    # overloaded (2) member must merge WORST-OF (2) — a sum (3) would
    # invent a state past "overloaded" and a healthy+sick pair would read
    # sick twice as hard as it is
    ha_, hb_ = MetricsRegistry(namespace="m"), MetricsRegistry(namespace="m")
    ha_.gauge("engine_health", agg="max").set(1.0)
    hb_.gauge("engine_health", agg="max").set(2.0)
    merged_h = FleetMetrics().add("e0", ha_).add("e1", hb_).merged()
    got = merged_h.get("engine_health").value
    if got != 2.0:
        errors.append(f"engine_health fleet merge is not worst-of: "
                      f"max(1, 2) merged to {got} (sum semantics leaked in)")
    # and the live engine's own health gauge max-folds with itself
    same = FleetMetrics().add("a", eng).add("b", eng).merged()
    one = eng.metrics.get("engine_health").value
    if same.get("engine_health").value != one:
        errors.append(f"engine_health self-merge {same.get('engine_health').value} "
                      f"!= member value {one} (agg must be max)")


def check_obs_server(eng, rid, errors):
    """Endpoint smoke over a real loopback socket (ephemeral port, daemon
    thread): /metrics parses, /stats carries the stats schema, /requests/
    <rid> serves the exemplar's span tree, /debug is a valid bundle, and an
    unknown rid is a clean 404."""
    import urllib.error
    import urllib.request

    from paddle_tpu.inference.obs_server import ObservabilityServer

    def get(srv, route, accept=None):
        req = urllib.request.Request(
            srv.url + route,
            headers={"Accept": accept} if accept else {})
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, r.read().decode("utf-8")
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode("utf-8")

    with ObservabilityServer(eng) as srv:
        # OpenMetrics negotiation carries the exemplars...
        status, text = get(srv, "/metrics",
                           accept="application/openmetrics-text")
        if status != 200:
            errors.append(f"/metrics -> {status}")
        check_exposition(text, errors)
        if not parse_prometheus_full(text)[1]:
            errors.append("/metrics (openmetrics) carries no exemplar")
        # ...while a plain 0.0.4 scrape must get exemplar-free text (stock
        # Prometheus text-format parsers reject the suffix)
        status, plain = get(srv, "/metrics")
        if status != 200:
            errors.append(f"/metrics (plain) -> {status}")
        check_exposition(plain, errors)
        if " # {" in plain:
            errors.append("plain /metrics scrape leaked exemplar syntax")
        status, text = get(srv, "/stats")
        st = json.loads(text) if status == 200 else {}
        missing = REQUIRED_STATS_KEYS - set(st)
        if status != 200 or missing:
            errors.append(f"/stats -> {status}, missing {sorted(missing)}")
        if rid is not None:
            status, text = get(srv, f"/requests/{rid}")
            if status != 200 or not json.loads(text).get("traceEvents"):
                errors.append(f"/requests/{rid} -> {status} (no span tree)")
        status, text = get(srv, "/requests/1234567")
        if status != 404:
            errors.append(f"/requests/<unknown> -> {status}, want 404")
        status, text = get(srv, "/debug")
        bundle = json.loads(text) if status == 200 else {}
        missing = REQUIRED_DEBUG_BUNDLE_KEYS - set(bundle)
        if status != 200 or missing:
            errors.append(f"/debug -> {status}, missing {sorted(missing)}")
        # /healthz is the REAL health evaluation now: a structured state
        # with per-signal detail, never the old hardcoded {"ok": true}
        status, text = get(srv, "/healthz")
        health = json.loads(text)
        if set(health) == {"ok"}:
            errors.append("/healthz is still the hardcoded liveness stub")
        if health.get("state") not in HEALTH_STATES:
            errors.append(f"/healthz state {health.get('state')!r} unknown")
        if status not in (200, 503) or \
                (status == 503) != (health.get("state") == "overloaded"):
            errors.append(f"/healthz -> {status} with state "
                          f"{health.get('state')!r} (want 200 for "
                          f"ok/degraded, 503 for overloaded)")
        if "signals" not in health:
            errors.append("/healthz carries no per-signal detail")


def check_front_door(errors):
    """ONE door: a 2-replica dp fleet served by `ServingFrontend`, with the
    obs plane mounted on the same socket as `/v1/*`.  Asserts the door's
    `/metrics` is the FLEET exposition (per-engine series + `llm_fleet_*`
    merges equal to member sums), `/stats` maps per label, `/healthz` is
    the worst-of rollup (flips to 503 when one member goes overloaded),
    inference requests round-trip 200, and the 404 route list advertises
    the `/v1` endpoints next to the obs routes."""
    import urllib.error
    import urllib.request

    import jax
    import numpy as np

    from paddle_tpu.inference.frontend import ServingFrontend
    from paddle_tpu.inference.router import EngineFleet
    from paddle_tpu.models import gpt as G

    def get(url, accept=None):
        req = urllib.request.Request(
            url, headers={"Accept": accept} if accept else {})
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, r.read().decode("utf-8")
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode("utf-8")

    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(1))
    fleet = EngineFleet(params, cfg, replicas=2,
                        engine_kwargs=dict(num_slots=2, page_size=8,
                                           max_model_len=64,
                                           prefill_chunk=16, seed=0))
    if not fleet.shared_executables():
        errors.append("front-door fleet replicas did not adopt the "
                      "leader's compiled executables")
    fleet.start()
    door = ServingFrontend(fleet).start()
    try:
        # land one request on EACH replica (round-robin) so every per-engine
        # series carries real traffic, then one through the HTTP door itself
        rng = np.random.RandomState(3)
        for label in fleet.engines:
            h = fleet.submit(rng.randint(0, cfg.vocab_size, (12,)),
                             session=label, policy="round_robin",
                             max_new_tokens=3)
            if fleet.result(h, timeout=60.0) is None:
                errors.append(f"front-door warm request on {label} "
                              f"timed out")
        body = json.dumps({
            "prompt": [int(x) for x in rng.randint(0, cfg.vocab_size, (8,))],
            "max_tokens": 3}).encode("utf-8")
        req = urllib.request.Request(
            door.url + "/v1/completions", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                out = json.loads(r.read())
                if not out.get("choices", [{}])[0].get("token_ids"):
                    errors.append(f"front-door completion carried no "
                                  f"tokens: {out}")
        except urllib.error.HTTPError as e:
            errors.append(f"POST /v1/completions through the door -> "
                          f"{e.code}: {e.read()[:200]}")

        # /metrics THROUGH the door == the fleet exposition
        status, text = get(door.url + "/metrics")
        if status != 200:
            errors.append(f"front-door /metrics -> {status}")
        check_exposition(text, errors)
        try:
            samples = parse_prometheus(text)
        except ValueError as e:
            errors.append(f"front-door /metrics failed to parse: {e}")
            samples = {}
        per = {parse_labels(lbl).get("engine"): v for lbl, v in
               samples.get("llm_engine_decode_tokens_total", ())}
        if set(per) != set(fleet.engines):
            errors.append(f"front-door /metrics per-engine series "
                          f"{sorted(per)} != replicas "
                          f"{sorted(fleet.engines)}")
        elif min(per.values()) <= 0:
            errors.append(f"a replica served traffic but its per-engine "
                          f"decode_tokens series is empty: {per}")
        total = samples.get("llm_fleet_decode_tokens_total",
                            [("", -1)])[0][1]
        if total != sum(per.values()):
            errors.append(f"front-door llm_fleet_decode_tokens_total "
                          f"{total} != member sum {sum(per.values())}")

        status, text = get(door.url + "/stats")
        st = json.loads(text) if status == 200 else {}
        if status != 200 or set(st) != set(fleet.engines):
            errors.append(f"front-door /stats -> {status}, labels "
                          f"{sorted(st)}")
        status, text = get(door.url + "/healthz")
        health = json.loads(text)
        if status != 200 or health.get("state") not in HEALTH_STATES or \
                set(health.get("engines", {})) != set(fleet.engines):
            errors.append(f"front-door /healthz -> {status}: {health}")
        # worst-of: wedge ONE member into overloaded — the fleet rollup
        # must flip to 503/overloaded while the other member stays ok
        eng1 = fleet.engines["engine1"]
        real_health = eng1.health
        eng1.health = lambda: {"state": "overloaded", "code": 2,
                               "reasons": ["forced by check_metrics"],
                               "signals": {}, "burn_rates": {}}
        try:
            status, text = get(door.url + "/healthz")
            health = json.loads(text)
            if status != 503 or health.get("state") != "overloaded":
                errors.append(f"front-door /healthz is not worst-of: one "
                              f"overloaded member -> {status} "
                              f"{health.get('state')!r} (want 503 "
                              f"overloaded)")
        finally:
            eng1.health = real_health

        status, text = get(door.url + "/no-such-route")
        routes = json.loads(text).get("routes", []) if status == 404 else []
        if status != 404 or "POST /v1/completions" not in routes or \
                "/metrics" not in routes:
            errors.append(f"front-door 404 route list does not advertise "
                          f"both planes: {status} {routes}")
    finally:
        door.close()
        fleet.stop()


def check_disagg(errors):
    """Disaggregated-serving observability (ISSUE 17): a 1P:1D role fleet
    serving a returning conversation must move the `kv_handoff_*` counters
    on the prefill replica and `kv_tier_restores` on the decode replica,
    stamp a `handoff` event on the prefill request's timeline, and expose
    role-labeled per-engine health through the serving front door."""
    import urllib.error
    import urllib.request

    import jax
    import numpy as np

    from paddle_tpu.inference.frontend import ServingFrontend
    from paddle_tpu.inference.router import EngineFleet
    from paddle_tpu.models import gpt as G

    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(2))
    fleet = EngineFleet(params, cfg, roles="P:D",
                        engine_kwargs=dict(num_slots=2, page_size=8,
                                           max_model_len=64,
                                           prefill_chunk=16, seed=2))
    fleet.warm()
    fleet.start()
    door = ServingFrontend(fleet).start()
    try:
        rng = np.random.RandomState(5)
        conv = list(rng.randint(0, cfg.vocab_size, (20,)).astype(np.int32))
        for _turn in range(2):
            h = fleet.submit(np.asarray(conv, np.int32), session="s0",
                             max_new_tokens=4)
            out = fleet.result(h, timeout=120.0)
            if out is None:
                errors.append("disagg smoke turn timed out")
                return
            conv = conv + list(out.token_ids)
        pe = fleet.engines[fleet.prefill_pool[0]]
        de = fleet.engines[fleet.decode_pool[0]]
        pc = pe.metrics.snapshot()["counters"]
        for k in ("kv_handoff_exports", "kv_handoff_pages",
                  "kv_handoff_tokens"):
            if pc.get(k, 0) < 1:
                errors.append(f"disagg smoke: prefill counter {k} never "
                              f"moved ({pc.get(k, 0)})")
        if de.stats()["kv_tier"]["restores"] < 1:
            errors.append("disagg smoke: decode replica never tier-restored "
                          "a handed-off prefix")
        if fleet.stats()["disagg"]["handoffs"] < 1:
            errors.append("disagg smoke: fleet recorded no handoff")
        # the prefill request's timeline carries the handoff event (stamped
        # post-retirement, so it must land on the RETIRED trace)
        names = set()
        for rid in range(12):
            tree = pe.export_request_trace(rid)
            if isinstance(tree, dict):
                names |= {e.get("name") for e in tree.get("traceEvents", ())}
        if "handoff" not in names:
            errors.append(f"disagg smoke: no 'handoff' timeline event on "
                          f"any prefill request trace (saw {sorted(names)})")
        # role-labeled health through the front door
        try:
            with urllib.request.urlopen(door.url + "/healthz",
                                        timeout=10) as r:
                health = json.loads(r.read())
        except urllib.error.HTTPError as e:
            health = json.loads(e.read())
        per = health.get("engines", {})
        got = {l: per.get(l, {}).get("role") for l in fleet.engines}
        want = {l: fleet.engines[l].role for l in fleet.engines}
        if got != want:
            errors.append(f"front-door /healthz per-engine roles {got} != "
                          f"{want}")
    finally:
        door.close()
        fleet.stop()


def main() -> int:
    errors = []
    eng, st = run_smoke(errors)

    missing = REQUIRED_STATS_KEYS - set(st)
    if missing:
        errors.append(f"stats() missing keys: {sorted(missing)}")
    if not missing:
        lat_missing = REQUIRED_LATENCY_KEYS - set(st["latency"])
        if lat_missing:
            errors.append(f"stats()['latency'] missing: {sorted(lat_missing)}")
        slo_missing = REQUIRED_SLO_KEYS - set(st["slo"])
        if slo_missing:
            errors.append(f"stats()['slo'] missing: {sorted(slo_missing)}")
        # health & signals PR: the rates block carries every family over
        # every window, health folds to a known state, roofline is complete
        rates = st["rates"]
        miss = set(RATE_FAMILIES) - set(rates)
        if miss:
            errors.append(f"stats()['rates'] missing families: {sorted(miss)}")
        for fam in RATE_FAMILIES:
            wmiss = set(RATE_WINDOW_LABELS) - set(rates.get(fam, {}))
            if wmiss:
                errors.append(f"stats()['rates'][{fam!r}] missing windows: "
                              f"{sorted(wmiss)}")
        hmiss = REQUIRED_HEALTH_KEYS - set(st["health"])
        if hmiss:
            errors.append(f"stats()['health'] missing: {sorted(hmiss)}")
        elif st["health"]["state"] not in HEALTH_STATES:
            errors.append(f"unknown health state {st['health']['state']!r}")
        rmiss = REQUIRED_ROOFLINE_KEYS - set(st["roofline"])
        if rmiss:
            errors.append(f"stats()['roofline'] missing: {sorted(rmiss)}")
        tmiss = REQUIRED_KV_TIER_KEYS - set(st["kv_tier"])
        if tmiss:
            errors.append(f"stats()['kv_tier'] missing: {sorted(tmiss)}")

    snap = eng.metrics.snapshot()
    for section, required in (("counters", REQUIRED_COUNTERS),
                              ("gauges", REQUIRED_GAUGES),
                              ("histograms", REQUIRED_HISTOGRAMS)):
        miss = required - set(snap.get(section, {}))
        if miss:
            errors.append(f"snapshot()[{section!r}] missing: {sorted(miss)}")
    try:
        json.dumps(snap)
    except TypeError as e:
        errors.append(f"snapshot() is not JSON-serializable: {e}")

    check_exposition(eng.metrics.to_prometheus(), errors)
    rid = check_exemplar_roundtrip(eng, errors)
    check_merge_and_fleet(eng, errors)
    check_obs_server(eng, rid, errors)
    check_front_door(errors)
    check_disagg(errors)

    # observability must be free of compiled programs: decode-side budget
    # unchanged — the bound comes from the registry (declared ONCE) so this
    # guard cannot drift from check_program_count's
    from paddle_tpu.analysis.registry import SERVE_PROGRAM_BUDGET
    bound = SERVE_PROGRAM_BUDGET["decode_side_executables"]
    decode_side = st["decode_executables"] + st["verify_executables"]
    if decode_side > bound:
        errors.append(f"decode-side executables {decode_side} > {bound} with "
                      f"metrics enabled — instrumentation leaked into a "
                      f"compiled program")

    report = {"metric": "serve_metrics_schema", "ok": not errors,
              "decode_side_executables": decode_side,
              "prefix_evictions": st["prefix_evictions"],
              "spec_events": st["spec_events"],
              "aborted_requests": st["aborted_requests"],
              "exemplar_rid": rid,
              "errors": errors}
    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    print(json.dumps(report))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
