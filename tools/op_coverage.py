"""Op-surface coverage report vs the reference's public export lists.

Usage: python tools/op_coverage.py [--reference /root/reference]

Extracts the reference's `python/paddle/tensor/__init__.py` tensor_method_func
list and `python/paddle/__init__.py` __all__, unions them, and diffs against
what `paddle_tpu` actually exports.  The VERDICT round-1 target was >=80% of
reference tensor exports; this is the burn-down tool.
"""
from __future__ import annotations

import argparse
import re


def reference_exports(ref_root: str):
    names = set()
    with open(f"{ref_root}/python/paddle/tensor/__init__.py") as f:
        m = re.search(r"tensor_method_func = \[(.*?)\]", f.read(), re.S)
        if m:
            names |= set(re.findall(r"'(\w+)'", m.group(1)))
    with open(f"{ref_root}/python/paddle/__init__.py") as f:
        m = re.search(r"__all__ = \[(.*?)\]", f.read(), re.S)
        if m:
            names |= set(re.findall(r"'(\w+)'", m.group(1)))
    return sorted(names)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    ap.add_argument("--modules", action="store_true",
                    help="also check paddle.fft / paddle.signal module exports")
    args = ap.parse_args()

    import paddle_tpu as paddle
    ref = reference_exports(args.reference)
    have = set(dir(paddle))
    missing = [n for n in ref if n not in have]
    pct = 100.0 * (1 - len(missing) / len(ref))
    print(f"top-level + tensor exports: {len(ref) - len(missing)}/{len(ref)} "
          f"({pct:.1f}%)")
    if missing:
        print("missing:", ", ".join(missing))

    for mod in ("fft", "signal", "linalg", "nn", "nn.functional", "distribution",
                "distributed", "amp", "io", "jit", "metric", "optimizer",
                "sparse", "vision", "static", "incubate", "autograd"):
        ref_path = f"{args.reference}/python/paddle/{mod.replace('.', '/')}"
        try:
            with open(f"{ref_path}/__init__.py") as f:
                m = re.search(r"__all__ = \[(.*?)\]", f.read(), re.S)
        except FileNotFoundError:
            try:
                with open(f"{ref_path}.py") as f:
                    m = re.search(r"__all__ = \[(.*?)\]", f.read(), re.S)
            except FileNotFoundError:
                continue
        if not m:
            continue
        ref_names = sorted(set(re.findall(r"'(\w+)'", m.group(1))))
        if not ref_names:
            continue
        cur = paddle
        try:
            for part in mod.split("."):
                cur = getattr(cur, part)
        except AttributeError:
            print(f"paddle.{mod}: MODULE MISSING ({len(ref_names)} exports)")
            continue
        sub_missing = [n for n in ref_names if not hasattr(cur, n)]
        sub_pct = 100.0 * (1 - len(sub_missing) / len(ref_names))
        line = f"paddle.{mod}: {len(ref_names) - len(sub_missing)}/{len(ref_names)} ({sub_pct:.0f}%)"
        if sub_missing:
            line += "  missing: " + ", ".join(sub_missing[:25])
            if len(sub_missing) > 25:
                line += f" ... +{len(sub_missing) - 25}"
        print(line)


if __name__ == "__main__":
    main()
