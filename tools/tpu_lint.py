#!/usr/bin/env python
"""tpu_lint: static analysis proving the engine's dispatch/sync discipline.

Two levels under one entry point (reference counterpart: the `tools/` CI
layer of custom static checks — op-registry audits, API guards):

- **AST** (`paddle_tpu/analysis/visitor.py`): TPL001 host-sync in
  step()-reachable code, TPL002 jit/shard_map site not in
  `analysis/registry.py`, TPL003 missing donation on hot buffers, TPL004
  Python branch on a traced value, TPL005 untimed blocking device fetch,
  TPL006 broad except around device code, TPL007 page-state mutation with a
  double-buffered dispatch in flight (harvest first), LINT000 suppression
  without a reason.  Suppress per line with
  `# tpu-lint: disable=TPL001 -- reason`.
- **jaxpr** (`analysis/jaxpr_checks.py`): traces the serving executables
  (the fused one-dispatch step AND the --no-fuse legacy trio, mp1+mp2) and
  audits the programs — JXP001 embedded transfers, JXP002 donation
  mismatches, JXP003 f64 upcasts, JXP004 missing mp sharding constraints,
  JXP005 oversized host-visible output (the fused step must return O(B*K)
  ints, never [B, V] logits).

Exit status is non-zero on any unsuppressed finding.

Usage:
  python tools/tpu_lint.py [paths...]         # default: paddle_tpu/
  python tools/tpu_lint.py --ci               # repo-wide, both levels (CI)
  python tools/tpu_lint.py --level ast f.py   # fast, no jax import
  python tools/tpu_lint.py --json ...         # machine-readable findings
  python tools/tpu_lint.py --list-rules
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the mp jaxpr pass needs virtual chips; must land before jax initializes
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")

CI_PATHS = ["paddle_tpu", "tools", "bench.py", "bench_serve.py"]


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: paddle_tpu/)")
    ap.add_argument("--level", choices=("ast", "jaxpr", "all"), default="all",
                    help="ast = source rules only (no jax import); jaxpr = "
                         "traced-program audits only; all = both (default)")
    ap.add_argument("--ci", action="store_true",
                    help=f"CI mode: lint {CI_PATHS} at --level all")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object with every finding "
                         "(suppressed included)")
    ap.add_argument("--no-mp", action="store_true",
                    help="skip the mp=2 jaxpr pass (single-device hosts)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args()

    if args.list_rules:
        from paddle_tpu.analysis import rule_table
        for code, title, rationale in rule_table():
            print(f"{code}  {title:34s} {rationale}")
        return 0

    paths = args.paths or (CI_PATHS if args.ci else ["paddle_tpu"])
    level = "all" if args.ci else args.level
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [p if os.path.exists(p) else os.path.join(repo, p)
             for p in paths]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        # a typo'd path must not report "clean" — that is how a CI job lints
        # nothing forever
        print(f"tpu_lint: no such path(s): {missing}", file=sys.stderr)
        return 2
    from paddle_tpu.analysis import iter_python_files
    if not iter_python_files(paths):
        # same guard for the subtler shape of the mistake: the paths exist
        # but contain nothing lintable
        print(f"tpu_lint: no python files under {paths}", file=sys.stderr)
        return 2

    findings = []
    if level in ("ast", "all"):
        from paddle_tpu.analysis import run_ast_checks
        findings.extend(run_ast_checks(paths))
    if level in ("jaxpr", "all"):
        # the jaxpr targets are the serving executables — only meaningful
        # when the lint scope covers the serving engine
        in_scope = any(
            os.path.isdir(p) and (
                os.path.exists(os.path.join(p, "inference", "engine.py")) or
                os.path.exists(os.path.join(p, "engine.py")))
            or p.endswith("engine.py")
            for p in paths)
        if in_scope:
            from paddle_tpu.analysis import run_jaxpr_checks
            findings.extend(run_jaxpr_checks(include_mp=not args.no_mp))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    live = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    if args.json:
        print(json.dumps({
            "tool": "tpu_lint", "level": level, "paths": paths,
            "ok": not live,
            "findings": [f.to_json() for f in findings],
            "live": len(live), "suppressed": len(suppressed),
        }))
    else:
        for f in live:
            print(f.format())
        print(f"tpu_lint: {len(live)} finding(s), "
              f"{len(suppressed)} suppressed", file=sys.stderr)
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
