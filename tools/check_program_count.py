#!/usr/bin/env python
"""CI guard: the serving engine's compiled-program budget.

Continuous batching is only viable on TPU because the engine runs a FIXED set
of executables regardless of traffic shape (README "Serving" section).  The
documented budget, which this script re-measures on every run so a future PR
cannot silently reintroduce per-shape recompiles:

- decode-side: <= 1 program — THE fused `serve_step_paged` executable
  (vanilla decode, spec verify and the interleaved prefill chunk all ride
  one fixed-shape batch, sampling + acceptance on device);
- prefill-side (chunked mode): <= 2 programs for the cold paths (the chunk
  rides the fused batch, so a chunked fused run measures 0);
- copy: <= 1 program (the COW page copy);
- swap: <= 2 programs — the KV swap-out gather + swap-in scatter, SHARED by
  preemption swap parking and the (default-on) KV tier's prefix
  spill/restore; warmed by `warm_swap`, so this stream measures exactly 2
  with zero tier-specific programs on top;
- total: <= 6.

The budget holds PER MESH CONFIG: a second pass re-measures under mp=2
tensor-parallel serving (8 forced CPU host devices — the same simulation the
multichip training dryrun uses) and asserts decode-side <= 1 there too.  The
mp engine AOT-compiles its executables, so the measured counts are exact
distinct-program counts, not dispatch-cache sizes.  (`--no-fuse` serving is
the A/B escape hatch and sits outside this budget — it is still audited by
tpu_lint's jaxpr level.)

A third pass measures a 2-replica dp `EngineFleet` (the serving front
door's scale-out unit): replication must ADD ZERO programs — replicas run
on the leader's mesh and adopt its compiled executables, so every
replica's counts stay inside the SAME single-engine budget and the
executable objects are asserted literally identical
(`EngineFleet.shared_executables`), not merely equal in number.

A fourth pass measures a disaggregated 1P:1D `EngineFleet` (ISSUE 17):
prefill/decode role separation moves KV between engines through the durable
host/disk tier store — pure host-side numpy + npz, so the handoff must mint
ZERO compiled programs.  The prefill replica's export rides the same warmed
swap-out gather and the decode replica's restore rides the same warmed
swap-in scatter that preemption parking declared, so BOTH role replicas
measure inside the unchanged single-engine budget with the executable
objects literally shared (leader adoption, same mesh) — and the pass
asserts at least one handoff actually crossed the store, so a silent
degrade to colocated serving cannot fake compliance.

Runs the bench_serve CPU smoke (chunked prefill + prefix cache + speculative
decoding — every lane the scheduler can dispatch) and exits non-zero with a
diff against the budget on violation.

The budget itself is DECLARED in `paddle_tpu/analysis/registry.py` (the
central program registry) — this script re-measures the live counts against
it, and `tools/tpu_lint.py` (TPL002) statically verifies no unregistered
jit/shard_map site can mint programs outside it.  One declaration, two
guards: the runtime check and the linter cannot drift apart.

Usage: JAX_PLATFORMS=cpu python tools/check_program_count.py
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the mp=2 pass needs virtual chips; must land before jax initializes
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")

from paddle_tpu.analysis.registry import (  # noqa: E402
    SERVE_PROGRAM_BUDGET as BUDGET,
    SERVE_PROGRAM_BUDGET_MP as BUDGET_MP)


def measure(mp=1):
    from bench_serve import run_serve_bench
    stats = run_serve_bench(num_requests=12, num_slots=2, page_size=8,
                            max_model_len=64, max_new_tokens=6,
                            prefill_chunk=16, prefix_cache=True,
                            shared_prefix_frac=0.5, spec_len=4, seed=11,
                            mp=mp)
    got = {
        "decode_side_executables": stats["decode_executables"] +
                                   stats["verify_executables"],
        "prefill_executables": stats["prefill_executables"],
        "copy_executables": stats["copy_executables"],
        # swap gather/scatter: warmed (and used by the default-on KV tier's
        # prefix spill/restore) on this stream — the tier must stay inside
        # the same <= 2 bucket preemption swapping declared
        "swap_executables": stats["swap_executables"],
    }
    got["total_executables"] = (got["decode_side_executables"] +
                                got["prefill_executables"] +
                                got["copy_executables"] +
                                got["swap_executables"])
    return got, stats


def measure_fleet(replicas=2):
    """dp replication adds ZERO programs: a 2-replica `EngineFleet` serving
    a mixed stream (chunked prefill + prefix hits + spec decode, spread
    round-robin so BOTH replicas dispatch) must keep every replica's
    executable counts inside the single-engine budget, with the executable
    objects literally shared (leader-adoption, same mesh).  Returns
    ({label: counts}, shared_executables)."""
    import jax
    import numpy as np

    from paddle_tpu.inference.router import EngineFleet
    from paddle_tpu.models import gpt as G

    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(11))
    fleet = EngineFleet(params, cfg, replicas=replicas,
                        engine_kwargs=dict(num_slots=2, page_size=8,
                                           max_model_len=64,
                                           prefill_chunk=16, spec_len=4,
                                           seed=11))
    fleet.warm()
    rng = np.random.RandomState(11)
    shared_prefix = rng.randint(0, cfg.vocab_size, (20,)).astype(np.int32)
    prompts = [shared_prefix,
               rng.randint(0, cfg.vocab_size, (9,)).astype(np.int32),
               np.concatenate([shared_prefix,
                               rng.randint(0, cfg.vocab_size,
                                           (7,)).astype(np.int32)]),
               rng.randint(0, cfg.vocab_size, (33,)).astype(np.int32)]
    with fleet:
        handles = [fleet.submit(p, session=f"s{i}", policy="round_robin",
                                max_new_tokens=6)
                   for i, p in enumerate(prompts)]
        for h in handles:
            if fleet.result(h, timeout=120.0) is None:
                raise RuntimeError(f"fleet program-count stream timed out "
                                   f"on {h}")
    per = {}
    for label, eng in fleet.engines.items():
        st = eng.stats()
        got = {
            "decode_side_executables": st["decode_executables"] +
                                       st["verify_executables"],
            "prefill_executables": st["prefill_executables"],
            "copy_executables": st["copy_executables"],
            "swap_executables": st["swap_executables"],
        }
        got["total_executables"] = sum(got.values())
        per[label] = got
    return per, fleet.shared_executables()


def measure_disagg():
    """Disaggregated serving adds ZERO programs: a 1P:1D role fleet serving
    a 2-session x 2-turn conversation stream (every returning turn is a
    store handoff: prefill exports through the durable tier, decode
    tier-restores) must keep BOTH role replicas' executable counts inside
    the single-engine budget with the compiled objects literally shared.
    Returns ({label: counts}, shared_executables, handoffs)."""
    import jax
    import numpy as np

    from paddle_tpu.inference.router import EngineFleet
    from paddle_tpu.models import gpt as G

    cfg = G.gpt_tiny(64)
    params = G.init_params(cfg, jax.random.key(11))
    fleet = EngineFleet(params, cfg, roles="P:D",
                        engine_kwargs=dict(num_slots=2, page_size=8,
                                           max_model_len=64,
                                           prefill_chunk=16, spec_len=4,
                                           seed=11))
    fleet.warm()
    rng = np.random.RandomState(11)
    convs = [list(rng.randint(0, cfg.vocab_size, (18,)).astype(np.int32))
             for _ in range(2)]
    with fleet:
        for _turn in range(2):
            for s in range(2):
                h = fleet.submit(np.asarray(convs[s], np.int32),
                                 session=f"s{s}", max_new_tokens=6)
                out = fleet.result(h, timeout=120.0)
                if out is None:
                    raise RuntimeError("disagg program-count stream timed "
                                       f"out on session s{s}")
                convs[s] = convs[s] + list(out.token_ids)
    per = {}
    for label, eng in fleet.engines.items():
        st = eng.stats()
        got = {
            "decode_side_executables": st["decode_executables"] +
                                       st["verify_executables"],
            "prefill_executables": st["prefill_executables"],
            "copy_executables": st["copy_executables"],
            "swap_executables": st["swap_executables"],
        }
        got["total_executables"] = sum(got.values())
        per[f"{label}:{eng.role}"] = got
    handoffs = fleet.stats()["disagg"]["handoffs"]
    return per, fleet.shared_executables(), handoffs


def main() -> int:
    rc = 0
    report = {"metric": "serve_compiled_program_count", "ok": True}
    digests = {}
    # mp4 rides the same MP budget: the fused program PARTITIONS over the
    # mesh, it does not fork — the vocab-sharded head included (the sharded
    # argmax/sample merges live inside the one fused executable)
    for mp, budget in ((1, BUDGET), (2, BUDGET_MP), (4, BUDGET_MP)):
        got, stats = measure(mp=mp)
        digests[mp] = stats["outputs_digest"]
        over = {k: (got[k], budget[k]) for k in budget if got[k] > budget[k]}
        tag = f"mp{mp}"
        report[tag] = {"budget": budget, "measured": got,
                       "accepted_per_step": stats["accepted_per_step"],
                       "ok": not over}
        if over:
            report["ok"] = False
            rc = 1
            for k, (g, b) in over.items():
                print(f"FAIL[{tag}]: {k} = {g} exceeds documented budget {b} "
                      f"— a code path is recompiling per shape; see README "
                      f"'Serving'", file=sys.stderr)
    # mp serving must be a pure partitioning of the same computation: every
    # pass replays the same stream, so greedy outputs must match BYTE-exactly
    # across the whole mesh ladder (the sharded argmax/top-k tie-break is
    # deterministic by construction)
    report["mp_parity"] = digests[1] == digests[2] == digests[4]
    if not report["mp_parity"]:
        report["ok"] = False
        rc = 1
        print("FAIL: mp>1 serving outputs diverge from single-chip (greedy "
              "token parity broken across the mesh ladder)", file=sys.stderr)
    # dp fleet pass: replication shares the leader's compiled set — every
    # replica inside the SAME single-engine budget, executables identical
    fleet_per, fleet_shared = measure_fleet()
    report["fleet"] = {"replicas": len(fleet_per), "budget": BUDGET,
                       "shared_executables": fleet_shared,
                       "per_replica": fleet_per, "ok": fleet_shared}
    if not fleet_shared:
        report["ok"] = False
        rc = 1
        print("FAIL[fleet]: replicas are not sharing the leader's compiled "
              "executables — dp replication is minting duplicate programs",
              file=sys.stderr)
    for label, got in fleet_per.items():
        over = {k: (got[k], BUDGET[k]) for k in BUDGET if got[k] > BUDGET[k]}
        if over:
            report["ok"] = report["fleet"]["ok"] = False
            rc = 1
            for k, (g, b) in over.items():
                print(f"FAIL[fleet/{label}]: {k} = {g} exceeds documented "
                      f"budget {b} — dp replication must not widen the "
                      f"per-replica program set", file=sys.stderr)
    # disagg pass: role separation must not widen the program set — the
    # handoff is host-side store traffic riding the warmed swap bucket
    dis_per, dis_shared, dis_handoffs = measure_disagg()
    report["disagg"] = {"roles": "P:D", "budget": BUDGET,
                        "shared_executables": dis_shared,
                        "handoffs": dis_handoffs,
                        "per_replica": dis_per,
                        "ok": dis_shared and dis_handoffs >= 1}
    if not dis_shared:
        report["ok"] = False
        rc = 1
        print("FAIL[disagg]: role replicas are not sharing the leader's "
              "compiled executables — disaggregation is minting duplicate "
              "programs", file=sys.stderr)
    if dis_handoffs < 1:
        report["ok"] = False
        rc = 1
        print("FAIL[disagg]: no prefill->decode handoff crossed the store "
              "(the pass degraded to colocated serving and proves nothing)",
              file=sys.stderr)
    for label, got in dis_per.items():
        over = {k: (got[k], BUDGET[k]) for k in BUDGET if got[k] > BUDGET[k]}
        if over:
            report["ok"] = report["disagg"]["ok"] = False
            rc = 1
            for k, (g, b) in over.items():
                print(f"FAIL[disagg/{label}]: {k} = {g} exceeds documented "
                      f"budget {b} — the tier-store handoff must stay "
                      f"host-side (zero new programs)", file=sys.stderr)
    print(json.dumps(report))
    return rc


if __name__ == "__main__":
    sys.exit(main())
