#!/usr/bin/env python
"""CI guard: the serving engine's compiled-program budget.

Continuous batching is only viable on TPU because the engine runs a FIXED set
of executables regardless of traffic shape (README "Serving" section).  The
documented budget, which this script re-measures on every run so a future PR
cannot silently reintroduce per-shape recompiles:

- decode-side: <= 2 programs (vanilla `decode_step_paged` + the spec-decode
  `verify_step_paged`) — one token or spec_len+1 tokens per slot per step,
  nothing else;
- prefill-side (chunked mode): <= 2 programs (the q_offset chunk executable;
  the bucketed ladder is off);
- copy: <= 1 program (the COW page copy);
- total: <= 5.

Runs the bench_serve CPU smoke (chunked prefill + prefix cache + speculative
decoding — every lane the scheduler can dispatch) and exits non-zero with a
diff against the budget on violation.

Usage: JAX_PLATFORMS=cpu python tools/check_program_count.py
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGET = {
    "decode_side_executables": 2,   # decode + verify
    "prefill_executables": 2,
    "copy_executables": 1,
    "total_executables": 5,
}


def measure():
    from bench_serve import run_serve_bench
    stats = run_serve_bench(num_requests=12, num_slots=2, page_size=8,
                            max_model_len=64, max_new_tokens=6,
                            prefill_chunk=16, prefix_cache=True,
                            shared_prefix_frac=0.5, spec_len=4, seed=11)
    got = {
        "decode_side_executables": stats["decode_executables"] +
                                   stats["verify_executables"],
        "prefill_executables": stats["prefill_executables"],
        "copy_executables": stats["copy_executables"],
    }
    got["total_executables"] = (got["decode_side_executables"] +
                                got["prefill_executables"] +
                                got["copy_executables"])
    return got, stats


def main() -> int:
    got, stats = measure()
    over = {k: (got[k], BUDGET[k]) for k in BUDGET if got[k] > BUDGET[k]}
    print(json.dumps({"metric": "serve_compiled_program_count",
                      "budget": BUDGET, "measured": got,
                      "accepted_per_step": stats["accepted_per_step"],
                      "ok": not over}))
    if over:
        for k, (g, b) in over.items():
            print(f"FAIL: {k} = {g} exceeds documented budget {b} — a code "
                  f"path is recompiling per shape; see README 'Serving'",
                  file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
