#!/usr/bin/env python
"""CI guard + trajectory keeper for the serving bench.

The HBM/collective/program budgets are declared once and re-measured every
run (`tools/tpu_cost.py`, `tools/check_program_count.py`); serving PERF had
no such discipline — each PR's `bench_serve.py` JSON line scrolled away and
nothing noticed a regression until a human did.  This tool closes that gap:

- **Trajectory** (`BENCH_SERVE.jsonl`): every bench run appends ONE
  schema-versioned row — the mode axes that make rows comparable across PRs
  (mp, fuse, spec, dtypes, oversubscribe, tracing) plus the key perf
  metrics (tokens/s, goodput, dispatches/step, host-sync ms, fused_speedup,
  parity flags, tracing overhead, roofline predicted/measured/model_error).
  `bench_serve.py` writes the row by default (`--no-history` opts out)
  through `append_bench_row()` here, so the row shape and its validator
  live in one file.
- **Floors** (`--ci`): runs a fresh CPU-smoke bench (subprocess, exactly
  what a human would run — `--replicas 2 --disagg P:D` so the dp-fleet and
  disaggregated prefill/decode passes run too)
  and enforces `SERVE_PERF_FLOORS` — declared ONCE in
  `paddle_tpu/analysis/registry.py` next to the resource budgets: every
  parity flag true (fleet_parity included), dispatches/step within the
  decode-side program budget, fused_speedup over its floor, the
  deterministic tracing account under 2%, model_error a sane positive
  ratio, and on fleet rows the affinity-vs-round-robin prefix-hit odds
  ratio >= 1 with replicas sharing the leader's compiled programs.  The
  passing row is appended, so a green CI run IS a trajectory point.

Exits non-zero with a diff on violation.  Usage:
    JAX_PLATFORMS=cpu python tools/check_bench.py --ci      # bench + floors
    python tools/check_bench.py                             # history schema
    python tools/check_bench.py --from-json out.json        # external row
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_HISTORY = os.path.join(_REPO, "BENCH_SERVE.jsonl")

ROW_SCHEMA_VERSION = 5

# the axes that make rows comparable across PRs: two rows agree on "mode"
# or their perf numbers are not the same experiment.  v1 rows (pre KV
# tiering) validate against the v1 sets — old history stays parseable.
MODE_AXES_V1 = ("mp", "fused", "spec_len", "prefill_chunk", "weight_dtype",
                "kv_dtype", "oversubscribe", "preempt_mode", "admission",
                "request_tracing")
# v2 (KV tiering PR): the tier switch and the multi-turn session axes
MODE_AXES_V2 = MODE_AXES_V1 + ("kv_tier", "multi_turn",
                               "session_return_frac")
# v3 (serving front door PR): the dp fleet axes — replica count + routing
# policy (router is null on single-engine rows)
MODE_AXES_V3 = MODE_AXES_V2 + ("replicas", "router")
# v4 (disaggregated serving PR): the prefill/decode role split ("P:D" on
# disagg rows, null otherwise) and the engine-restart restore sub-pass
MODE_AXES = MODE_AXES_V3 + ("disagg", "restart")
# the perf surface a trajectory reader plots; absent-in-this-mode metrics
# (e.g. goodput_ratio without --oversubscribe) ride as null
PERF_KEYS_V1 = ("decode_tokens_per_sec_per_chip", "generated_tokens_per_sec",
                "goodput_tokens_per_sec", "goodput_ratio",
                "dispatches_per_step", "host_sync_ms_per_step",
                "predicted_step_ms", "measured_step_ms", "model_error",
                "roofline_drift", "steady_state_recompiles",
                "fused_speedup", "spec_speedup", "accepted_per_step",
                "tracing_overhead", "tracing_overhead_measured",
                "preemptions_per_step", "prefix_hit_rate",
                "ttft_p50_ms", "ttft_p99_ms", "tpot_p99_ms",
                "requests", "elapsed_s", "device_spec")
# v2: tier spill/restore traffic + the returning-session view the tier's
# win is measured on (prefilled_tokens rides along so the drop is
# recomputable from any two rows)
PERF_KEYS_V2 = PERF_KEYS_V1 + (
    "prefilled_tokens", "resume_hits", "resume_restored_tokens",
    "partial_page_hits", "returning_prefilled_tokens",
    "returning_prefilled_drop", "returning_ttft_p50_ms")
# v3: the fleet surface — requested-router throughput/balance plus the
# affinity-vs-round-robin A/B on the identical session stream
PERF_KEYS_V3 = PERF_KEYS_V2 + (
    "fleet_generated_tokens_per_sec", "replica_balance", "fleet_shed",
    "affinity_prefix_hit_rate", "round_robin_prefix_hit_rate",
    "affinity_prefix_hit_ratio", "affinity_returning_ttft_p50_ms",
    "round_robin_returning_ttft_p50_ms", "fleet_shared_executables")
# v4: the disaggregation surface — store-handoff latency, the prefill-
# interference delta on decode TPOT, and the restart restore sub-pass
PERF_KEYS_V4 = PERF_KEYS_V3 + (
    "handoff_p50_ms", "handoff_p99_ms", "handoff_count",
    "interference_tpot_delta_ms", "restart_restored_tokens",
    "restart_ttft_ms")
# v5 (vocab-sharded head PR): the at-rest param-placement surface — per-
# device replicated vs sharded bytes next to the fp wte size, so the
# "replicated embedding ceiling" stays visibly retired across PRs
PERF_KEYS = PERF_KEYS_V4 + (
    "replicated_bytes_per_device", "sharded_bytes_per_device", "wte_bytes")
PARITY_KEYS = ("fuse_parity", "spec_parity", "oversubscribe_parity",
               "tracing_parity", "kv_tier_parity", "fleet_parity",
               "disagg_parity")
REQUIRED_ROW_KEYS = frozenset({"schema_version", "t", "mode", "perf",
                               "parity"})
_AXES_BY_VERSION = {1: (MODE_AXES_V1, PERF_KEYS_V1),
                    2: (MODE_AXES_V2, PERF_KEYS_V2),
                    3: (MODE_AXES_V3, PERF_KEYS_V3),
                    4: (MODE_AXES, PERF_KEYS_V4),
                    5: (MODE_AXES, PERF_KEYS)}


def bench_row(stats, t=None):
    """Project one `bench_serve` result dict onto the trajectory row."""
    return {
        "schema_version": ROW_SCHEMA_VERSION,
        "t": time.time() if t is None else float(t),
        "mode": {k: stats.get(k) for k in MODE_AXES},
        "perf": {k: stats.get(k) for k in PERF_KEYS},
        # only the parity flags this run's comparison passes produced
        "parity": {k: stats[k] for k in PARITY_KEYS if k in stats},
    }


def validate_row(row):
    """Schema check for one trajectory row; returns error strings."""
    errors = []
    if not isinstance(row, dict):
        return [f"row is not an object: {type(row).__name__}"]
    missing = REQUIRED_ROW_KEYS - set(row)
    if missing:
        errors.append(f"row missing keys: {sorted(missing)}")
        return errors
    if row["schema_version"] not in _AXES_BY_VERSION:
        errors.append(f"schema_version {row['schema_version']!r} not in "
                      f"{sorted(_AXES_BY_VERSION)} (migrate the row or bump "
                      f"the reader)")
        return errors
    mode_axes, perf_keys = _AXES_BY_VERSION[row["schema_version"]]
    if not isinstance(row["t"], (int, float)) or row["t"] <= 0:
        errors.append(f"bad timestamp t={row['t']!r}")
    for section, keys in (("mode", mode_axes), ("perf", perf_keys)):
        if not isinstance(row[section], dict):
            errors.append(f"row[{section!r}] is not an object")
            continue
        miss = set(keys) - set(row[section])
        if miss:
            errors.append(f"row[{section!r}] missing axes: {sorted(miss)}")
    if not isinstance(row["parity"], dict):
        errors.append("row['parity'] is not an object")
    tok = (row.get("perf") or {}).get("decode_tokens_per_sec_per_chip")
    if not isinstance(tok, (int, float)):
        errors.append(f"perf.decode_tokens_per_sec_per_chip is not a "
                      f"number: {tok!r}")
    return errors


def check_floors(row, floors=None):
    """Enforce `SERVE_PERF_FLOORS` on one row; returns error strings.  Mode-
    conditional bars (dispatch cap, fused_speedup) apply only where the row's
    mode reaches them; the parity and tracing bars apply wherever the run
    produced the number."""
    if floors is None:
        from paddle_tpu.analysis.registry import SERVE_PERF_FLOORS
        floors = SERVE_PERF_FLOORS
    errors = []
    perf = row.get("perf") or {}
    mode = row.get("mode") or {}
    for k in floors["parity_flags"]:
        v = row.get("parity", {}).get(k)
        if v is not None and v is not True:
            errors.append(f"parity flag {k} is {v!r} — byte-exact parity is "
                          f"the one bar noise cannot excuse")
    tok = perf.get("decode_tokens_per_sec_per_chip")
    if not isinstance(tok, (int, float)) or \
            tok < floors["tokens_per_sec_min"]:
        errors.append(f"decode_tokens_per_sec_per_chip {tok!r} below "
                      f"{floors['tokens_per_sec_min']}")
    if mode.get("fused"):
        d = perf.get("dispatches_per_step")
        cap = floors["dispatches_per_step_max"]
        if not isinstance(d, (int, float)) or d > cap + 1e-9:
            errors.append(f"dispatches_per_step {d!r} exceeds the declared "
                          f"{cap} (the one-dispatch claim broke)")
        fs = perf.get("fused_speedup")
        if fs is not None and fs < floors["fused_speedup_min"]:
            errors.append(f"fused_speedup {fs} below the declared floor "
                          f"{floors['fused_speedup_min']}")
    # bench_row fills absent keys with None, so fall back on None — not
    # just on a missing key — or a raw run_serve_bench row (which carries
    # only the measured account) would skip the tracing bar entirely
    overhead = perf.get("tracing_overhead")
    if overhead is None:
        overhead = perf.get("tracing_overhead_measured")
    if overhead is not None and overhead >= floors["tracing_overhead_max"]:
        errors.append(f"tracing overhead {overhead} at or above the "
                      f"{floors['tracing_overhead_max']} bar")
    me = perf.get("model_error")
    if me is None or not (0.0 < me <= floors["model_error_max"]):
        errors.append(f"model_error {me!r} outside "
                      f"(0, {floors['model_error_max']}] — the roofline "
                      f"prediction is missing or broken")
    # KV-tier capacity floor: deterministic (token counts, not wall clock)
    # wherever a multi-turn row ran the tier comparison pass
    drop = perf.get("returning_prefilled_drop")
    drop_min = floors.get("returning_prefilled_drop_min")
    if drop is not None and drop_min is not None and \
            mode.get("kv_tier") and (mode.get("multi_turn") or 1) > 1 and \
            drop < drop_min:
        errors.append(f"returning_prefilled_drop {drop} below the declared "
                      f"{drop_min} — returning sessions are re-prefilling "
                      f"KV the tier should have restored")
    # affinity-routing floor: deterministic (token-count hit rates, not
    # wall clock) on any row whose mode ran the fleet passes
    ratio = perf.get("affinity_prefix_hit_ratio")
    ratio_min = floors.get("affinity_prefix_hit_ratio_min")
    if (mode.get("replicas") or 1) > 1 and ratio_min is not None:
        if not isinstance(ratio, (int, float)) or ratio < ratio_min:
            errors.append(f"affinity_prefix_hit_ratio {ratio!r} below the "
                          f"declared {ratio_min} — affinity routing is "
                          f"hitting the prefix cache no better than the "
                          f"cache-blind round-robin baseline")
        if perf.get("fleet_shared_executables") is not True:
            errors.append("fleet_shared_executables is not True — dp "
                          "replicas stopped adopting the leader's compiled "
                          "programs (replication must add zero executables)")
    # vocab-sharded head floor: at mp>=2 the per-device replicated param
    # bytes must sit STRICTLY below the fp wte size — the exact ceiling the
    # sharded layout retired.  Deterministic (byte counts off the cached
    # cost account, not wall clock); only v5+ rows carry the fields.
    if floors.get("replicated_below_wte") and (mode.get("mp") or 1) >= 2:
        rep = perf.get("replicated_bytes_per_device")
        wte = perf.get("wte_bytes")
        if isinstance(rep, (int, float)) and isinstance(wte, (int, float)) \
                and rep >= wte:
            errors.append(f"replicated_bytes_per_device {rep} not strictly "
                          f"below wte_bytes {wte} at mp>=2 — the embedding/"
                          f"head replication ceiling is back")
    # disaggregation floor: every handoff must complete within the declared
    # ceiling (a store handoff slower than a re-prefill defeats the split)
    if mode.get("disagg"):
        hp99 = perf.get("handoff_p99_ms")
        cap = floors.get("handoff_p99_ms_max")
        if cap is not None and (not isinstance(hp99, (int, float)) or
                                hp99 > cap):
            errors.append(f"handoff_p99_ms {hp99!r} missing or above the "
                          f"declared {cap} ceiling — prefill->decode store "
                          f"handoff is slower than the re-prefill it "
                          f"replaces")
    return errors


def append_bench_row(stats, path=DEFAULT_HISTORY, t=None):
    """`bench_serve.py`'s post-run hook: build, validate and append the
    trajectory row; returns it.  Raises ValueError on a malformed result —
    a bench that cannot produce a valid row must fail loudly, not seed the
    trajectory with garbage."""
    row = bench_row(stats, t=t)
    errors = validate_row(row)
    if errors:
        raise ValueError(f"bench result does not project onto a valid "
                         f"trajectory row: {errors}")
    with open(path, "a") as f:
        f.write(json.dumps(row, sort_keys=True) + "\n")
    return row


def read_history(path=DEFAULT_HISTORY):
    """((line_no, row) pairs, error strings) for every line of the
    trajectory file; a missing file is an empty (valid) trajectory."""
    rows, errors = [], []
    if not os.path.exists(path):
        return rows, errors
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                errors.append(f"{path}:{i}: not JSON: {e}")
                continue
            errors.extend(f"{path}:{i}: {e}" for e in validate_row(row))
            rows.append((i, row))
    return rows, errors


def run_ci_bench():
    """Run the CPU-smoke bench exactly as a human would (subprocess,
    `--no-history` so THIS tool owns the append) and return its result
    dict."""
    import subprocess
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench_serve.py"),
         "--no-history", "--replicas", "2", "--disagg", "P:D"],
        capture_output=True, text=True, cwd=_REPO, env=env, timeout=1800)
    if proc.returncode != 0:
        raise RuntimeError(f"bench_serve.py failed (rc={proc.returncode}):\n"
                           f"{proc.stderr[-4000:]}")
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"no JSON line in bench_serve.py output:\n"
                       f"{proc.stdout[-2000:]}")


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ci", action="store_true",
                    help="run a fresh CPU-smoke bench, enforce "
                         "SERVE_PERF_FLOORS, append the passing row")
    ap.add_argument("--from-json", type=str, default=None,
                    help="validate + floor-check an existing bench_serve "
                         "result JSON (the printed line) instead of running")
    ap.add_argument("--history", type=str, default=DEFAULT_HISTORY,
                    help="trajectory file (default BENCH_SERVE.jsonl at the "
                         "repo root)")
    ap.add_argument("--no-append", action="store_true",
                    help="check only; do not append the row")
    args = ap.parse_args(argv)

    errors = []
    row = None
    stats = None
    if args.ci:
        stats = run_ci_bench()
    elif args.from_json:
        with open(args.from_json) as f:
            stats = json.load(f)
    if stats is not None:
        row = bench_row(stats)
        errors.extend(validate_row(row))
        errors.extend(check_floors(row))
    # the drop-in schema pass over the whole trajectory (also the default
    # no-args mode) runs BEFORE any append: a red run must not mutate the
    # trajectory (reruns would stack duplicate rows on a broken history) —
    # a green CI run IS a trajectory point, a red one leaves no trace
    rows, hist_errors = read_history(args.history)
    errors.extend(hist_errors)
    if row is not None and not errors and not args.no_append:
        with open(args.history, "a") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
        rows.append((len(rows) + 1, row))

    report = {"metric": "serve_bench_trajectory", "ok": not errors,
              "history": args.history, "history_rows": len(rows),
              "appended": bool(row is not None and not errors
                               and not args.no_append),
              "errors": errors}
    if row is not None:
        report["row_perf"] = {
            k: row["perf"].get(k)
            for k in ("decode_tokens_per_sec_per_chip", "dispatches_per_step",
                      "fused_speedup", "tracing_overhead", "model_error")}
        report["row_parity"] = row["parity"]
    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    print(json.dumps(report))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
