"""Benchmark: flagship GPT pretraining tokens/sec/chip on one real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config: GPT-3 1.3B architecture (hidden 2048, 24 layers, 16 heads, seq 2048),
bf16 params + bf16 Adam moments + remat — the single-chip projection of baseline
ladder #4.  vs_baseline is measured tokens/sec/chip divided by 3500 (a Megatron-LM
A100 per-chip figure for GPT-3 1.3B; the reference repo publishes no in-tree numbers
— see BASELINE.md), so vs_baseline >= 0.9 meets the ladder #4 bar.
"""
from __future__ import annotations

import json
import time

import numpy as np


A100_BASELINE_TOKENS_PER_SEC = 3500.0


def main():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.parallel import HybridParallelTrainer, MeshConfig

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if on_tpu:
        config = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                           num_heads=16, max_seq_len=2048, dtype=jnp.bfloat16)
        batch, seq, steps = 4, 2048, 8
    else:  # CI smoke: tiny
        config = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                           num_heads=4, max_seq_len=256)
        batch, seq, steps = 4, 256, 3

    trainer = HybridParallelTrainer(config, MeshConfig(remat=True),
                                    moment_dtype=jnp.bfloat16)
    rng = np.random.RandomState(0)
    tok = rng.randint(0, config.vocab_size, (batch, seq)).astype(np.int32)
    lab = np.roll(tok, -1, axis=1).astype(np.int32)

    # warmup/compile (host-read the loss: a device->host transfer is the only sync
    # that provably waits for execution on remote-tunneled backends)
    loss = trainer.train_step(tok, lab)
    _ = float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.train_step(tok, lab)
    final = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final)

    tokens_per_sec = batch * seq * steps / dt
    # model FLOPs/token (PaLM-appendix convention): 6*N + causal attention term
    from paddle_tpu.models.gpt import count_params
    n_params = count_params(trainer.params)
    gflop_per_tok = (6 * n_params
                     + 6 * config.num_layers * seq * config.hidden_size) / 1e9
    v5e_peak_tf = 197.0  # bf16
    mfu = tokens_per_sec * gflop_per_tok / 1e3 / v5e_peak_tf
    print(json.dumps({
        "metric": "gpt3_1.3b_pretrain_tokens_per_sec_per_chip" if on_tpu
                  else "gpt_tiny_tokens_per_sec (cpu smoke)",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tokens_per_sec / A100_BASELINE_TOKENS_PER_SEC, 3)
                       if on_tpu else 0.0,
        "mfu_v5e": round(mfu, 3) if on_tpu else None,
    }))


if __name__ == "__main__":
    main()
