"""GPT family — the flagship model (baseline ladder #4: GPT-3 1.3B hybrid parallel).

Two faces over one implementation:
- a pure-functional core (`init_params` / `forward` / `loss_fn`) over a stacked-block
  params pytree — the compiled hybrid-parallel trainer consumes this directly;
- a `GPTForCausalLM` nn.Layer wrapper exposing the eager paddle-style API.

TPU-native choices: blocks are stacked on a leading L axis and run under `lax.scan`
(one compiled block, XLA-friendly, and the L axis is what pipeline parallelism
shards); attention is the Pallas flash kernel; norms hit the fused RMSNorm kernel;
RoPE is fused into the attention prologue.  Mirrors the reference's GPT in
PaddleNLP structure (embed -> L x [ln, attn, ln, mlp] -> ln -> tied lm head).
"""
from __future__ import annotations

import dataclasses
import math
from collections import OrderedDict
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from ..incubate.kernels.flash_attention import flash_attention_fused
from ..incubate.kernels.rms_norm import rms_norm_fused
from ..incubate.kernels.rope import apply_rope


@dataclasses.dataclass
class GPTConfig:
    """One transformer-family config covering GPT / LLaMA / BERT architectures.

    The reference implements these as separate model zoos (PaddleNLP gpt/llama/
    bert); TPU-first we keep ONE stacked-block functional core and express the
    family differences as config axes — every member then rides the same
    compiled hybrid-parallel trainer unchanged.
    """
    vocab_size: int = 50304
    hidden_size: int = 2048
    num_layers: int = 24
    num_heads: int = 16
    max_seq_len: int = 2048
    intermediate_size: Optional[int] = None
    use_rope: bool = True
    use_rms_norm: bool = False  # GPT-3 uses LayerNorm; llama preset flips this
    activation: str = "gelu"
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    dtype: Any = jnp.float32
    # --- architecture axes beyond GPT ---
    num_kv_heads: Optional[int] = None  # GQA (llama-2/3): kv heads < q heads
    gated_ffn: bool = False     # SwiGLU: down(act(gate(x)) * up(x))
    use_bias: bool = True       # llama drops all linear biases
    causal: bool = True         # False = bidirectional encoder (BERT)
    norm_position: str = "pre"  # "post" = BERT-style residual-then-norm
    embed_norm: bool = False    # BERT: LayerNorm right after the embeddings
    final_norm: bool = True     # BERT (post-LN) has no final encoder norm
    type_vocab_size: int = 0    # BERT segment (token-type) embeddings
    mlm_head: bool = False      # BERT MLM transform (dense+act+LN) before head
    # MoE (ref incubate/distributed/models/moe): >0 replaces the dense FFN with
    # moe_num_experts capacity-routed experts in every block
    moe_num_experts: int = 0
    moe_topk: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @property
    def ffn_size(self):
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self):
        return self.num_kv_heads or self.num_heads

    @property
    def qkv_dim(self):
        """Packed q|k|v output width: D + 2 * kv_heads * head_dim."""
        return self.hidden_size + 2 * self.kv_heads * self.head_dim


def gpt3_1p3b():
    """GPT-3 1.3B config (baseline ladder #4)."""
    return GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
                     max_seq_len=2048)


def gpt_tiny(seq_len=128):
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                     max_seq_len=seq_len)


def gpt_moe_tiny(seq_len=128, num_experts=4, capacity_factor=2.0):
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                     max_seq_len=seq_len, moe_num_experts=num_experts,
                     moe_capacity_factor=capacity_factor)


# ---------------------------------------------------------------------------
# functional core
# ---------------------------------------------------------------------------

def init_params(config: GPTConfig, key) -> Dict[str, Any]:
    c = config
    D, L, F, V = c.hidden_size, c.num_layers, c.ffn_size, c.vocab_size
    k = iter(jax.random.split(key, 24))
    std = c.initializer_range
    proj_std = std / math.sqrt(2 * L)  # GPT-2/3 residual-scaled init

    def norm_pair(shape):
        return jnp.ones(shape, c.dtype), jnp.zeros(shape, c.dtype)

    ln1_w, ln1_b = norm_pair((L, D))
    ln2_w, ln2_b = norm_pair((L, D))
    lnf_w, lnf_b = norm_pair((D,))
    blocks = {
        "ln1_w": ln1_w, "ln1_b": ln1_b,
        "qkv_w": (jax.random.normal(next(k), (L, D, c.qkv_dim)) * std).astype(c.dtype),
        "proj_w": (jax.random.normal(next(k), (L, D, D)) * proj_std).astype(c.dtype),
        "ln2_w": ln2_w, "ln2_b": ln2_b,
    }
    if c.use_bias:
        blocks["qkv_b"] = jnp.zeros((L, c.qkv_dim), c.dtype)
        blocks["proj_b"] = jnp.zeros((L, D), c.dtype)
    if c.moe_num_experts > 0:
        E = c.moe_num_experts
        blocks.update({
            "gate_w": (jax.random.normal(next(k), (L, D, E)) * std).astype(jnp.float32),
            "exp_fc1_w": (jax.random.normal(next(k), (L, E, D, F)) * std).astype(c.dtype),
            "exp_fc1_b": jnp.zeros((L, E, F), c.dtype),
            "exp_fc2_w": (jax.random.normal(next(k), (L, E, F, D)) * proj_std).astype(c.dtype),
            "exp_fc2_b": jnp.zeros((L, E, D), c.dtype),
        })
    else:
        blocks.update({
            "fc1_w": (jax.random.normal(next(k), (L, D, F)) * std).astype(c.dtype),
            "fc2_w": (jax.random.normal(next(k), (L, F, D)) * proj_std).astype(c.dtype),
        })
        if c.gated_ffn:
            blocks["fcg_w"] = (jax.random.normal(next(k), (L, D, F)) * std).astype(c.dtype)
        if c.use_bias:
            blocks["fc1_b"] = jnp.zeros((L, F), c.dtype)
            blocks["fc2_b"] = jnp.zeros((L, D), c.dtype)
            if c.gated_ffn:
                blocks["fcg_b"] = jnp.zeros((L, F), c.dtype)
    params = {
        "wte": (jax.random.normal(next(k), (V, D)) * std).astype(c.dtype),
        "blocks": blocks,
    }
    if c.final_norm or c.embed_norm:
        # post-LN encoders (BERT) reuse the lnf pair as the EMBEDDING norm
        params["lnf_w"], params["lnf_b"] = lnf_w, lnf_b
    if not c.use_rope:
        params["wpe"] = (jax.random.normal(next(k), (c.max_seq_len, D)) * std).astype(c.dtype)
    if c.type_vocab_size > 0:
        params["tte"] = (jax.random.normal(next(k), (c.type_vocab_size, D))
                         * std).astype(c.dtype)
    if c.mlm_head:
        params["mlm_w"] = (jax.random.normal(next(k), (D, D)) * std).astype(c.dtype)
        params["mlm_b"] = jnp.zeros((D,), c.dtype)
        params["mlm_ln_w"] = jnp.ones((D,), c.dtype)
        params["mlm_ln_b"] = jnp.zeros((D,), c.dtype)
    if not c.tie_word_embeddings:
        params["lm_head"] = (jax.random.normal(next(k), (D, V)) * std).astype(c.dtype)
    return params


def pvary_compat(x, axes):
    """Mark x varying over manual mesh axes (pvary was deprecated for pcast).
    Old JAX (< 0.5) has neither and no varying-axes tracking at all (shard_map
    runs check_rep=False there) — identity is the correct no-op."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axes)
    return x


def _norm(x, w, b, config):
    if config.use_rms_norm:
        return rms_norm_fused(x, w)
    mu = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
    var = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
    out = (x.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + 1e-5)
    return (out * w + b).astype(x.dtype)


def _rope_tables(config, S, pos_offset=None):
    D = config.head_dim
    inv = 1.0 / (10000.0 ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    t = jnp.arange(S, dtype=jnp.float32)
    if pos_offset is not None:
        # context-parallel seq shard / decode position (traced or plain int)
        t = t + jnp.asarray(pos_offset, jnp.float32)
    freqs = jnp.outer(t, inv)
    return jnp.sin(freqs), jnp.cos(freqs)


def block_forward(bp, x, config: GPTConfig, mp_constraint=None, moe_impl=None,
                  attn_impl=None, pos_offset=None):
    """One transformer block; bp holds this block's (unstacked) weights.

    mp_constraint: optional callable applying sharding constraints on activations
    (set by the hybrid trainer to pin the tensor-parallel layout).
    moe_impl: optional callable (bp, x2d, config) -> (y2d, aux) overriding the
    MoE FFN (the hybrid trainer injects the ep-axis all-to-all version).
    attn_impl: optional callable (q, k, v) -> out overriding causal flash
    attention (the cp trainer injects ring attention).
    pos_offset: traced global position of x[:, 0] (context-parallel shards).

    Returns (out, aux) where aux is the MoE load-balance loss (0.0 when dense).
    """
    c = config
    B, S, D = x.shape
    H, KVH, hd = c.num_heads, c.kv_heads, c.head_dim
    pre = c.norm_position == "pre"

    h = _norm(x, bp["ln1_w"], bp["ln1_b"], c) if pre else x
    qkv = jnp.matmul(h, bp["qkv_w"])
    if "qkv_b" in bp:
        qkv = qkv + bp["qkv_b"]
    if mp_constraint:
        qkv = mp_constraint(qkv, "hidden_mp")
    q, kk, v = jnp.split(qkv, [H * hd, (H + KVH) * hd], axis=-1)
    q = q.reshape(B, S, H, hd)
    kk = kk.reshape(B, S, KVH, hd)
    v = v.reshape(B, S, KVH, hd)
    if c.use_rope:
        sin, cos = _rope_tables(c, S, pos_offset)
        q = apply_rope(q, sin, cos)
        kk = apply_rope(kk, sin, cos)
    if KVH != H:
        # GQA: each kv head serves H/KVH query heads (ref llama GQA repeat);
        # materializing the repeat keeps the flash kernel's H-uniform layout
        kk = jnp.repeat(kk, H // KVH, axis=2)
        v = jnp.repeat(v, H // KVH, axis=2)
    # saved under remat_policy_save_attention: the block replay then DCEs the qkv
    # matmul + rope (their only consumers' values are saved), keeping replay to
    # the proj/mlp chain
    q = checkpoint_name(q, "flash_qkv")
    kk = checkpoint_name(kk, "flash_qkv")
    v = checkpoint_name(v, "flash_qkv")
    if attn_impl is not None:
        attn = attn_impl(q, kk, v)
    else:
        attn = flash_attention_fused(q, kk, v, causal=c.causal)
    attn = attn.reshape(B, S, D)
    attn = jnp.matmul(attn, bp["proj_w"])
    if "proj_b" in bp:
        attn = attn + bp["proj_b"]
    x = x + attn
    if not pre:
        x = _norm(x, bp["ln1_w"], bp["ln1_b"], c)

    h = _norm(x, bp["ln2_w"], bp["ln2_b"], c) if pre else x
    if c.moe_num_experts > 0:
        from ..incubate.distributed.models.moe.dispatch import moe_ffn_dense
        fn = moe_impl or moe_ffn_dense
        y, aux = fn(bp, h.reshape(B * S, D), c)
        x = x + y.reshape(B, S, D)
        if not pre:
            x = _norm(x, bp["ln2_w"], bp["ln2_b"], c)
        return x, aux
    up = jnp.matmul(h, bp["fc1_w"])
    if "fc1_b" in bp:
        up = up + bp["fc1_b"]
    act = jax.nn.gelu if c.activation == "gelu" else jax.nn.silu
    if c.gated_ffn:
        gate = jnp.matmul(h, bp["fcg_w"])
        if "fcg_b" in bp:
            gate = gate + bp["fcg_b"]
        if mp_constraint:
            up = mp_constraint(up, "ffn_mp")
            gate = mp_constraint(gate, "ffn_mp")
        h = act(gate) * up
    else:
        if mp_constraint:
            up = mp_constraint(up, "ffn_mp")
        h = act(up)
    h = jnp.matmul(h, bp["fc2_w"])
    if "fc2_b" in bp:
        h = h + bp["fc2_b"]
    x = x + h
    if not pre:
        x = _norm(x, bp["ln2_w"], bp["ln2_b"], c)
    return x, jnp.zeros((), jnp.float32)


def run_blocks(blocks, x, config, mp_constraint=None, remat=False, moe_impl=None,
               attn_impl=None, pos_offset=None):
    """Scan the stacked blocks: one compiled block body, L iterations.

    Returns (out, aux) — aux is the summed MoE load-balance loss over blocks."""
    from ..incubate.kernels.flash_attention import remat_policy_save_attention

    body = block_forward
    if remat:
        # config AND mp_constraint are static so sharding constraints survive
        # remat.  The policy saves the flash-attention out/lse residuals, so the
        # block replay re-runs only the (cheap) matmul chain — attention forward
        # runs exactly once per step instead of ~3x (round-1 remat tax).
        body = jax.checkpoint(block_forward, static_argnums=(2, 3, 4, 5),
                              policy=remat_policy_save_attention())

    def step(carry, bp):
        x, aux = carry
        out, a = body(bp, x, config, mp_constraint, moe_impl, attn_impl,
                      pos_offset)
        return (out, aux + a), None

    # inside a shard_map (pp loop) x is varying over the manual axes; the aux
    # carry must carry the same vma type or scan rejects the carry signature
    aux0 = jnp.zeros((), jnp.float32)
    vma = getattr(jax.typeof(x), "vma", None) if hasattr(jax, "typeof") else None
    if vma:
        aux0 = pvary_compat(aux0, tuple(vma))
    (out, aux), _ = jax.lax.scan(step, (x, aux0), blocks)
    return out, aux


def embed_prologue(params, x, config: GPTConfig, type_ids=None):
    """Everything between the token-table lookup and the first block:
    learned positions, segment (token-type) embeddings, embedding norm.
    type_ids default to segment 0 (single-sentence BERT batches)."""
    S = x.shape[1]
    if not config.use_rope:
        x = x + params["wpe"][:S]
    if config.type_vocab_size > 0:
        if type_ids is None:
            x = x + params["tte"][0]
        else:
            x = x + jnp.take(params["tte"], type_ids, axis=0)
    if config.embed_norm:
        x = _norm(x, params["lnf_w"], params["lnf_b"], config)
    return x


def epilogue(params, h, config: GPTConfig):
    """Final norm (pre-LN stacks) and/or the BERT MLM transform
    (dense + act + LN, ref BertPretrainingHeads) before the vocab head."""
    if config.final_norm:
        h = _norm(h, params["lnf_w"], params["lnf_b"], config)
    if config.mlm_head:
        h = jnp.matmul(h, params["mlm_w"]) + params["mlm_b"]
        h = jax.nn.gelu(h) if config.activation == "gelu" else jax.nn.silu(h)
        h = _norm(h, params["mlm_ln_w"], params["mlm_ln_b"], config)
    return h


def _deq(q, scale, dtype):
    """Traced twin of `quantization.serving.dequantize_weight`: int8 values
    times float32 per-channel scale, cast into the compute dtype.  EVERY
    in-program weight dequant (blocks, embedding rows, head) goes through
    this one expression so the scheme cannot desynchronize between sites."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def _w(bp, name, dtype):
    """Weight `name` from a (possibly weight-quantized) param subtree.

    `quantization.serving.quantize_serving_params` replaces a serving matmul
    weight with the pair `name_q` (int8) + `name_scale` (float32, per output
    channel); this helper dequantizes it on the fly into the compute dtype.
    Called inside the layer scan, so the fp copy of a quantized weight only
    ever exists one block at a time — at-rest HBM stays int8."""
    q = bp.get(name + "_q")
    if q is None:
        return bp[name]
    return _deq(q, bp[name + "_scale"], dtype)


def _mesh_mp(mesh) -> int:
    """Tensor-parallel degree of a serving mesh (1 when mesh is None or has
    no "mp" axis) — the one switch the mp-aware serving fns key off."""
    if mesh is None:
        return 1
    return int(mesh.shape.get("mp", 1))


def _embed(params, tokens, config: GPTConfig, mesh=None):
    """Token-table lookup, weight-quantization aware: int8 `wte_q` rows are
    gathered first and dequantized by their per-row scale — the fp table is
    never materialized.

    Under an mp serving mesh the table is VOCAB-SHARDED (`wte` rows split
    over "mp" by `parallel.hybrid.serving_param_specs`), and the lookup runs
    as the Megatron vocab-parallel form — masked LOCAL take + psum inside a
    manual region, mirroring the trainer's `_vp_embed` — because a
    vocab-sharded gather under auto axes CHECK-crashes XLA's SPMD
    partitioner.  Exactly one shard owns each token id, so the psum of
    masked rows is bit-exact vs the replicated take."""
    mp = _mesh_mp(mesh)
    if mp <= 1:
        if "wte_q" in params:
            rows = jnp.take(params["wte_q"], tokens, axis=0)
            scale = jnp.take(params["wte_scale"], tokens, axis=0)
            return _deq(rows, scale, config.dtype)
        return jnp.take(params["wte"], tokens, axis=0)

    from jax.sharding import PartitionSpec as P
    from ..parallel.ring_attention import shard_map_compat
    quant = "wte_q" in params

    def local(table, scale, tok):
        r = jax.lax.axis_index("mp")
        Vl = table.shape[0]
        ids = tok - r * Vl
        ok = (ids >= 0) & (ids < Vl)
        safe = jnp.clip(ids, 0, Vl - 1)
        rows = jnp.take(table, safe, axis=0)
        if quant:
            rows = _deq(rows, jnp.take(scale, safe, axis=0), config.dtype)
        rows = jnp.where(ok[..., None], rows, jnp.zeros((), rows.dtype))
        return jax.lax.psum(rows, "mp")

    sm = shard_map_compat(
        local, mesh=mesh, axis_names={"mp"},
        in_specs=(P("mp", None), P("mp", None), P()), out_specs=P())
    if quant:
        return sm(params["wte_q"], params["wte_scale"], tokens)
    # fp path: feed the scale slot a zero-width view so one signature serves
    # both dtypes (the branch is static, the dummy is dead code when traced).
    return sm(params["wte"], params["wte"][:, :0], tokens)


def head_matrix(params, config: GPTConfig):
    if config.tie_word_embeddings:
        if "wte_q" in params:
            return _deq(params["wte_q"], params["wte_scale"],
                        config.dtype).T
        return params["wte"].T
    if "lm_head_q" in params:
        return _deq(params["lm_head_q"], params["lm_head_scale"],
                    config.dtype)
    return params["lm_head"]


def head_logits(x, params, config: GPTConfig, mesh=None):
    """Vocab projection `x @ head` for the serving executables.

    Quantization-aware WITHOUT materializing the fp [V, D] table inside the
    step (at real vocab sizes that transient alone would blow the declared
    peak-HBM budgets): the matmul runs against the int8 table upcast to the
    compute dtype — int8 values are exact in bf16/f32 — and the per-vocab
    scales multiply the LOGITS columns afterward, which is the same math
    because the scale is constant along the contraction dim.  The transient
    is logits-shaped, not weight-shaped.

    Under an mp mesh the head weight arrives VOCAB-SHARDED over "mp"
    (`serving_param_specs`), the matmul partitions as a plain local GEMM
    against the shard (matmuls — unlike gathers — partition fine under auto
    GSPMD), and the constraint pins the logits' vocab axis sharded so each
    chip holds [.., V/mp] and the replicated [.., V] buffer NEVER
    materializes; the downstream pick merges per-shard (value, index) pairs
    (`sharded_argmax` / `sample_token`)."""
    if config.tie_word_embeddings and "wte_q" in params:
        scale = params["wte_scale"].T                       # [V, 1] -> [1, V]
        logits = (jnp.matmul(x, params["wte_q"].T.astype(config.dtype))
                  * scale).astype(config.dtype)
    elif not config.tie_word_embeddings and "lm_head_q" in params:
        scale = params["lm_head_scale"]                     # already [1, V]
        logits = (jnp.matmul(x, params["lm_head_q"].astype(config.dtype))
                  * scale).astype(config.dtype)
    else:
        logits = jnp.matmul(x, head_matrix(params, config))
    if _mesh_mp(mesh) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = P(*([None] * (logits.ndim - 1)), "mp")
        logits = jax.lax.with_sharding_constraint(
            logits, NamedSharding(mesh, spec))
    return logits


def sharded_argmax(logits, mesh=None):
    """First-occurrence argmax over the vocab (last) axis, mp-aware.

    mesh None / mp=1 is plain `jnp.argmax`.  Under an mp mesh the logits
    arrive vocab-sharded and each chip reduces its local shard to a
    (value, global index) pair; a pmax merges the value and the tie-break
    takes the LOWEST global index among the shards holding the max (pmin
    over index-where-max, V as the sentinel) — exactly `jnp.argmax`'s
    first-occurrence rule, so mp∈{1,2,4} emit byte-identical tokens.  The
    merge runs in a manual region and moves one scalar pair per row over
    the mesh — the replicated [.., V] logits buffer never exists."""
    if _mesh_mp(mesh) <= 1:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    from jax.sharding import PartitionSpec as P
    from ..parallel.ring_attention import shard_map_compat
    V = logits.shape[-1]
    lead = logits.ndim - 1

    def local(lg):
        r = jax.lax.axis_index("mp")
        Vl = lg.shape[-1]
        lv = jnp.max(lg, axis=-1)
        li = jnp.argmax(lg, axis=-1).astype(jnp.int32) + r * Vl
        gm = jax.lax.pmax(lv, "mp")
        cand = jnp.where(lv == gm, li, V)
        return jax.lax.pmin(cand, "mp").astype(jnp.int32)

    return shard_map_compat(
        local, mesh=mesh, axis_names={"mp"},
        in_specs=(P(*([None] * lead), "mp"),), out_specs=P())(logits)


def backbone(params, tokens, config: GPTConfig, mp_constraint=None, remat=False,
             moe_impl=None, type_ids=None):
    """Shared trunk: tokens [B, S] -> (activations [B, S, D], head, moe aux)."""
    x = jnp.take(params["wte"], tokens, axis=0)
    x = embed_prologue(params, x, config, type_ids)
    if mp_constraint:
        x = mp_constraint(x, "act")
    x, aux = run_blocks(params["blocks"], x, config, mp_constraint, remat=remat,
                        moe_impl=moe_impl)
    x = epilogue(params, x, config)
    return x, head_matrix(params, config), aux


def forward(params, tokens, config: GPTConfig, mp_constraint=None, remat=False):
    """tokens [B, S] int32 -> logits [B, S, V]."""
    x, head, _ = backbone(params, tokens, config, mp_constraint, remat)
    return jnp.matmul(x, head)


def _ce_sums(logits, labels):
    """(-sum log p[label], count) over valid labels (-100 = ignore)."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    safe = jnp.where(labels < 0, 0, labels)
    picked = jnp.take_along_axis(lp, safe[..., None], axis=-1)[..., 0]
    mask = (labels >= 0).astype(jnp.float32)
    return -jnp.sum(picked * mask), jnp.sum(mask)


def loss_fn(params, tokens, labels, config: GPTConfig, mp_constraint=None,
            remat=False, loss_chunk: Optional[int] = 512, moe_impl=None):
    """Causal LM loss; labels [B, S] with -100 = ignore.

    loss_chunk: when set, the LM head + softmax run over sequence chunks inside a
    rematerialized scan, so the [B, S, V] float32 log-probs never materialize —
    the dominant HBM transient at GPT-3 vocab (V=50k: 3.3 GB at B=8, S=2048).
    """
    x, head, aux = backbone(params, tokens, config, mp_constraint, remat, moe_impl)
    moe_pen = config.moe_aux_weight * aux if config.moe_num_experts > 0 else 0.0
    B, S, D = x.shape
    if not loss_chunk or S % loss_chunk != 0 or S <= loss_chunk:
        loss_sum, n = _ce_sums(jnp.matmul(x, head), labels)
        return loss_sum / jnp.maximum(n, 1.0) + moe_pen

    nc = S // loss_chunk
    xc = jnp.swapaxes(x.reshape(B, nc, loss_chunk, D), 0, 1)       # [nc,B,c,D]
    labc = jnp.swapaxes(labels.reshape(B, nc, loss_chunk), 0, 1)

    def body(carry, xl):
        xx, ll = xl
        ls, n = _ce_sums(jnp.matmul(xx, head), ll)
        return (carry[0] + ls, carry[1] + n), None

    # remat the chunk: backward replays the chunk's head matmul instead of saving
    # per-chunk log-probs (head flops are ~5% of the model; the 3 GB is not)
    (loss_sum, n), _ = jax.lax.scan(jax.checkpoint(body), (0.0, 0.0), (xc, labc))
    return loss_sum / jnp.maximum(n, 1.0) + moe_pen


def count_params(params):
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))


# ---------------------------------------------------------------------------
# Layer wrapper (eager paddle-style API over the same functional core)
# ---------------------------------------------------------------------------

from ..core.tensor import Tensor, apply  # noqa: E402
from ..nn.layer.layers import Layer  # noqa: E402


class GPTForCausalLM(Layer):
    def __init__(self, config: GPTConfig = None, **kwargs):
        super().__init__()
        self.config = config or GPTConfig(**kwargs)
        from ..core import generator as _gen
        raw = init_params(self.config, _gen.next_key())
        from ..core.tensor import Parameter
        self._param_tree = jax.tree_util.tree_map(Parameter, raw)
        # register leaves so Layer machinery (state_dict, optimizers) sees them
        flat, self._treedef = jax.tree_util.tree_flatten(self._param_tree)
        for i, p in enumerate(flat):
            self.add_parameter(f"p{i}", p)
        self._flat_params = flat

    def forward(self, input_ids, labels=None):
        # run via apply so the tape records one whole-model node
        datas = [p for p in self._flat_params]
        tokens = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
        cfg = self.config
        if labels is not None:
            lab = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)

            def g(*leafs):
                tree = jax.tree_util.tree_unflatten(self._treedef, list(leafs))
                return loss_fn(tree, tokens, lab, cfg)
            return apply("gpt_loss", g, *datas)

        def h(*leafs):
            tree = jax.tree_util.tree_unflatten(self._treedef, list(leafs))
            return forward(tree, tokens, cfg)
        return apply("gpt_forward", h, *datas)

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k=None, eos_token_id=None):
        """KV-cache autoregressive decoding (see module-level `generate`)."""
        ids = input_ids._data if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        out = generate(self.params_pytree(), ids, self.config,
                       max_new_tokens=max_new_tokens, temperature=temperature,
                       top_k=top_k, eos_token_id=eos_token_id)
        return Tensor(out)

    def params_pytree(self):
        """Raw jnp pytree view (shared buffers) for the compiled trainer."""
        return jax.tree_util.tree_unflatten(
            self._treedef, [p._data for p in self._flat_params])

    def load_pytree(self, tree):
        flat, _ = jax.tree_util.tree_flatten(tree)
        for p, d in zip(self._flat_params, flat):
            p._data = d


def llama_tiny(seq_len=128):
    """Llama-architecture preset: RMSNorm + SwiGLU + GQA + no biases +
    untied head — the full architecture family, scaled tiny."""
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, max_seq_len=seq_len, use_rms_norm=True,
                     activation="silu", gated_ffn=True, use_bias=False,
                     tie_word_embeddings=False, intermediate_size=172)


def llama2_7b():
    """Llama-2 7B shape family (ref PaddleNLP llama configs)."""
    return GPTConfig(vocab_size=32000, hidden_size=4096, num_layers=32,
                     num_heads=32, max_seq_len=4096, use_rms_norm=True,
                     activation="silu", gated_ffn=True, use_bias=False,
                     tie_word_embeddings=False, intermediate_size=11008)


def llama3_8b():
    """Llama-3 8B shape family: GQA with 8 kv heads."""
    return GPTConfig(vocab_size=128256, hidden_size=4096, num_layers=32,
                     num_heads=32, num_kv_heads=8, max_seq_len=8192,
                     use_rms_norm=True, activation="silu", gated_ffn=True,
                     use_bias=False, tie_word_embeddings=False,
                     intermediate_size=14336)


def bert_config(vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
                max_seq_len=512, type_vocab_size=2, intermediate_size=None):
    """BERT-architecture config (ref PaddleNLP bert): bidirectional post-LN
    encoder, learned positions, segment embeddings, embedding LayerNorm, MLM
    transform head tied to the embeddings.  NSP is intentionally dropped
    (modern MLM-only pretraining; RoBERTa recipe)."""
    return GPTConfig(vocab_size=vocab_size, hidden_size=hidden_size,
                     num_layers=num_layers, num_heads=num_heads,
                     max_seq_len=max_seq_len, use_rope=False, causal=False,
                     norm_position="post", embed_norm=True, final_norm=False,
                     type_vocab_size=type_vocab_size, mlm_head=True,
                     intermediate_size=intermediate_size)


def bert_base():
    """BERT-base (baseline ladder #3)."""
    return bert_config()


def bert_tiny(seq_len=128):
    return bert_config(vocab_size=256, hidden_size=64, num_layers=2,
                       num_heads=4, max_seq_len=seq_len)


# ---------------------------------------------------------------------------
# KV-cache autoregressive decoding (ref PaddleNLP generation + fused
# variable-length attention; TPU-native: static-shape cache + lax.scan decode)
# ---------------------------------------------------------------------------

def init_cache(config: GPTConfig, batch: int, max_len: int):
    """Per-layer KV cache [L, B, max_len, KVH, hd] (static shapes for jit).
    GQA caches only the kv heads — the cache shrinks by H/KVH (the point of
    GQA for serving)."""
    c = config
    shape = (c.num_layers, batch, max_len, c.kv_heads, c.head_dim)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def _ffn_dense(bp, h, c: GPTConfig, mp_constraint=None):
    """Dense-FFN body shared by the decode/prefill paths (gated + bias aware,
    int8-weight aware via `_w`).  mp_constraint (serving tensor parallel)
    pins the column-sharded hidden."""
    up = jnp.matmul(h, _w(bp, "fc1_w", c.dtype))
    if "fc1_b" in bp:
        up = up + bp["fc1_b"]
    act = jax.nn.gelu if c.activation == "gelu" else jax.nn.silu
    if c.gated_ffn:
        gate = jnp.matmul(h, _w(bp, "fcg_w", c.dtype))
        if "fcg_b" in bp:
            gate = gate + bp["fcg_b"]
        if mp_constraint:
            up = mp_constraint(up, "ffn_mp")
            gate = mp_constraint(gate, "ffn_mp")
        h = act(gate) * up
    else:
        if mp_constraint:
            up = mp_constraint(up, "ffn_mp")
        h = act(up)
    out = jnp.matmul(h, _w(bp, "fc2_w", c.dtype))
    if "fc2_b" in bp:
        out = out + bp["fc2_b"]
    return out


def _unpack_qkv(qkv, c: GPTConfig, parts: int = 1):
    """Split a packed qkv matmul output into flat q/k/v column groups,
    partition-aware.

    parts=1 is the trainer's global `[q | k | v]` layout.  parts=mp reads
    the PER-PARTITION layout `[q_0 k_0 v_0 | q_1 k_1 v_1 | ...]` the engine
    places under mp (`parallel.hybrid.pack_qkv_partitions`), whose `parts`
    contiguous column groups are exactly each chip's head slices — so the
    placed qkv shard is consumed where it lands, with no replicate→reslice
    staging.  Concatenating the per-partition q (then k, then v) segments
    restores GLOBAL head order, so for matching permutations the result is
    bit-identical to the parts=1 unpack of the unpermuted weight; every
    reshape/slice here moves along locally-owned axes (the packed column
    axis shards evenly over `parts`), so under GSPMD the unpack is free."""
    H, KVH, hd = c.num_heads, c.kv_heads, c.head_dim
    if parts <= 1:
        return jnp.split(qkv, [H * hd, (H + KVH) * hd], axis=-1)
    lead = qkv.shape[:-1]
    Hl, KVHl = H // parts, KVH // parts
    g = qkv.reshape(*lead, parts, (Hl + 2 * KVHl) * hd)
    q = g[..., :Hl * hd].reshape(*lead, H * hd)
    k = g[..., Hl * hd:(Hl + KVHl) * hd].reshape(*lead, KVH * hd)
    v = g[..., (Hl + KVHl) * hd:].reshape(*lead, KVH * hd)
    return q, k, v


def _decode_qkv(bp, x, c: GPTConfig, pos, parts: int = 1):
    """Pre-norm + packed qkv + rope for a single-token decode input.

    x [B, D]; pos is a scalar (dense contiguous cache) or a [B] vector
    (per-slot positions, the paged engine's slot-indexed decode).
    Returns post-rope q [B, H, hd], k, v [B, KVH, hd].  `parts` selects the
    packed-qkv column layout (`_unpack_qkv`)."""
    B = x.shape[0]
    H, KVH, hd = c.num_heads, c.kv_heads, c.head_dim
    h = _norm(x, bp["ln1_w"], bp["ln1_b"], c) if c.norm_position == "pre" \
        else x
    qkv = jnp.matmul(h, _w(bp, "qkv_w", c.dtype))
    if "qkv_b" in bp:
        qkv = qkv + bp["qkv_b"]
    q, k, v = _unpack_qkv(qkv, c, parts)
    q = q.reshape(B, H, hd)
    k = k.reshape(B, KVH, hd)
    v = v.reshape(B, KVH, hd)
    if c.use_rope:
        sin, cos = _rope_tables(c, 1, pos_offset=pos)
        if jnp.ndim(pos) > 0:
            # per-slot positions: tables are [B, half] -> feed apply_rope's
            # batched [B, S=1, half] branch
            sin, cos = sin[:, None], cos[:, None]
        q = apply_rope(q[:, None], sin, cos)[:, 0]
        k = apply_rope(k[:, None], sin, cos)[:, 0]
    return q, k, v


def _rope_tables_at(config, pos):
    """Rope sin/cos at explicit (possibly traced, per-batch) positions.
    pos [B, T] int32 -> tables [B, T, head_dim/2] for apply_rope's batched
    branch — the chunked-prefill path, whose chunk starts at q_offset != 0."""
    D = config.head_dim
    inv = 1.0 / (10000.0 ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    freqs = pos.astype(jnp.float32)[..., None] * inv
    return jnp.sin(freqs), jnp.cos(freqs)


def _prefill_qkv(bp, x, c: GPTConfig, pos=None, parts: int = 1):
    """Pre-norm + packed qkv + rope over a [B, T, D] prompt (positions
    0..T-1, or explicit per-batch positions `pos` [B, T] for chunked
    prefill).  Returns post-rope q [B, T, H, hd], k, v [B, T, KVH, hd].
    `parts` selects the packed-qkv column layout (`_unpack_qkv`)."""
    B, T, _ = x.shape
    H, KVH, hd = c.num_heads, c.kv_heads, c.head_dim
    h = _norm(x, bp["ln1_w"], bp["ln1_b"], c) if c.norm_position == "pre" \
        else x
    qkv = jnp.matmul(h, _w(bp, "qkv_w", c.dtype))
    if "qkv_b" in bp:
        qkv = qkv + bp["qkv_b"]
    q, k, v = _unpack_qkv(qkv, c, parts)
    q = q.reshape(B, T, H, hd)
    k = k.reshape(B, T, KVH, hd)
    v = v.reshape(B, T, KVH, hd)
    if c.use_rope:
        sin, cos = _rope_tables(c, T) if pos is None else _rope_tables_at(c, pos)
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
    return q, k, v


def _layer_tail(bp, x, attn, c: GPTConfig, mp_constraint=None):
    """Shared post-attention half of a decode/prefill block: out-proj +
    residual (+ post-LN) + FFN/MoE + residual (+ post-LN).  attn is the
    head-flattened [..., D] attention output, x the block input (same rank)."""
    if mp_constraint:
        # head-sharded attention flattens to a column-sharded hidden; pinning
        # it keeps the row-parallel proj matmul a local-contraction + psum
        attn = mp_constraint(attn, "hidden_mp")
    attn = jnp.matmul(attn, _w(bp, "proj_w", c.dtype))
    if "proj_b" in bp:
        attn = attn + bp["proj_b"]
    x = x + attn
    if c.norm_position != "pre":
        x = _norm(x, bp["ln1_w"], bp["ln1_b"], c)
    h = _norm(x, bp["ln2_w"], bp["ln2_b"], c) if c.norm_position == "pre" \
        else x
    if c.moe_num_experts > 0:
        from ..incubate.distributed.models.moe.dispatch import moe_ffn_dense
        lead = h.shape[:-1]
        y, _ = moe_ffn_dense(bp, h.reshape(-1, c.hidden_size), c)
        y = y.reshape(*lead, c.hidden_size)
    else:
        y = _ffn_dense(bp, h, c, mp_constraint)
    x = x + y
    if c.norm_position != "pre":
        x = _norm(x, bp["ln2_w"], bp["ln2_b"], c)
    return x


def decode_step(params, token, cache, pos, config: GPTConfig):
    """One autoregressive step: token [B] int32 at position `pos` (traced).

    Returns (logits [B, V], updated cache).  Attention is a dense dot against
    the cache with a position mask — at decode T=1 the MXU matmul IS the
    fused path; no flash kernel needed.
    """
    c = config
    assert c.causal, "KV-cache decoding requires a causal model"
    B = token.shape[0]
    D, H, KVH, hd = c.hidden_size, c.num_heads, c.kv_heads, c.head_dim
    G = H // KVH                                             # queries per kv head
    x = jnp.take(params["wte"], token, axis=0)               # [B, D]
    if not c.use_rope:
        x = x + jax.lax.dynamic_index_in_dim(params["wpe"], pos, keepdims=False)

    max_len = cache["k"].shape[2]
    kv_pos = jnp.arange(max_len)

    def layer(x, layer_in):
        bp, kc, vc = layer_in                               # caches [B,S,KVH,hd]
        q, k, v = _decode_qkv(bp, x, c, pos)
        kc = jax.lax.dynamic_update_slice_in_dim(kc, k[:, None], pos, axis=1)
        vc = jax.lax.dynamic_update_slice_in_dim(vc, v[:, None], pos, axis=1)
        # grouped attention against the KVH-head cache: q [B, KVH, G, hd]
        qg = q.reshape(B, KVH, G, hd)
        s = jnp.einsum("bkgd,bskd->bkgs", qg, kc,
                       preferred_element_type=jnp.float32) / math.sqrt(hd)
        s = jnp.where((kv_pos <= pos)[None, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("bkgs,bskd->bkgd", p.astype(vc.dtype), vc)
        x = _layer_tail(bp, x, attn.reshape(B, D), c)
        return x, (kc, vc)

    def scan_body(carry, inp):
        out, kv = layer(carry, inp)
        return out, kv

    x, (new_k, new_v) = jax.lax.scan(
        scan_body, x, (params["blocks"], cache["k"], cache["v"]))
    x = epilogue(params, x, c)
    return head_logits(x, params, c), {"k": new_k, "v": new_v}


def prefill(params, input_ids, config: GPTConfig, cache):
    """One batched forward over the prompt that also fills the KV cache.

    Returns (last-position logits [B, V], cache with positions [0, Tp) set).
    The prompt runs as ONE dense pass (MXU-sized matmuls + causal attention),
    not Tp serial decode steps.
    """
    c = config
    assert c.causal, "KV-cache decoding requires a causal model"
    B, Tp = input_ids.shape
    D, H, KVH, hd = c.hidden_size, c.num_heads, c.kv_heads, c.head_dim
    x = jnp.take(params["wte"], input_ids, axis=0)
    if not c.use_rope:
        x = x + params["wpe"][:Tp]

    def layer(x, layer_in):
        bp, kc, vc = layer_in
        q, k, v = _prefill_qkv(bp, x, c)
        kc = jax.lax.dynamic_update_slice_in_dim(kc, k, 0, axis=1)
        vc = jax.lax.dynamic_update_slice_in_dim(vc, v, 0, axis=1)
        if KVH != H:
            k = jnp.repeat(k, H // KVH, axis=2)
            v = jnp.repeat(v, H // KVH, axis=2)
        attn = flash_attention_fused(q, k, v, causal=True).reshape(B, Tp, D)
        x = _layer_tail(bp, x, attn, c)
        return x, (kc, vc)

    x, (new_k, new_v) = jax.lax.scan(
        lambda carry, inp: layer(carry, inp),
        x, (params["blocks"], cache["k"], cache["v"]))
    x = epilogue(params, x[:, -1], c)
    return head_logits(x, params, c), {"k": new_k, "v": new_v}


# ---------------------------------------------------------------------------
# Paged KV cache (ref vLLM PagedAttention, SOSP 2023): KV lives in a static
# pool of fixed-size pages + per-slot page tables, so serving memory scales
# with live tokens instead of B x max_seq_len.  `inference.engine.LLMEngine`
# owns the page accounting; these are the compiled model-side steps.
# ---------------------------------------------------------------------------

def init_paged_cache(config: GPTConfig, num_pages: int, page_size: int,
                     kv_dtype=None):
    """Per-layer paged KV pool [L, num_pages, page_size, KVH, hd].
    Page 0 is reserved as the null page: inactive slots and padded bucket
    tails write there, and it is never read (masked by per-slot length).

    kv_dtype="int8" stores int8 k/v plus per-token-per-head float32 scale
    lanes `k_scale`/`v_scale` [L, num_pages, page_size, KVH]: every KV write
    quantizes in-program (`_quantize_kv`) and the paged-attention kernels
    dequantize per page on read.  Per-token scales keep the token-granular
    write paths (decode append, chunked prefill, verify rollback, COW, swap)
    exact and write-order independent — a coarser per-page scale would need
    a lossy rescale of already-written tokens.  The default (None) is the
    byte-identical fp pool."""
    from ..quantization.serving import KV_SCALE_DTYPE, normalize_quant_dtype
    c = config
    shape = (c.num_layers, num_pages, page_size, c.kv_heads, c.head_dim)
    if normalize_quant_dtype(kv_dtype, "kv_dtype") == "int8":
        sshape = shape[:-1]
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(sshape, KV_SCALE_DTYPE),
                "v_scale": jnp.zeros(sshape, KV_SCALE_DTYPE)}
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def _quantize_kv(x):
    """Symmetric per-token-per-head int8 quantization of a KV write
    `[..., hd]` -> (int8 values [..., hd], float32 scale [...]).  Runs
    INSIDE the serving executables at every KV write; the matching dequant
    is `value * scale` in the paged-attention kernels/oracles."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(absmax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127.0, 127.0) \
        .astype(jnp.int8)
    return q, scale


def _kv_scales(kv):
    """The attention entries' kv_scales lane: (k_scale, v_scale) for a
    quantized per-layer pool slice, None for the fp pool."""
    if "k_scale" in kv:
        return kv["k_scale"], kv["v_scale"]
    return None


def serving_mp_constraint(mesh):
    """Sharding-constraint callable for the tensor-parallel serving path
    (multi-chip `LLMEngine`): pins activations so GSPMD partitions the paged
    executables Megatron-style instead of guessing.  Kinds: "heads" shards the
    second-to-last ([..., H|KVH, hd]) axis over mp (attention is per-head
    independent); "ffn_mp"/"hidden_mp" column-shard the last axis.  Returns
    None when mesh has no mp axis > 1, so call sites read
    `if pin: x = pin(x, kind)` — zero-cost single chip."""
    if mesh is None or int(dict(mesh.shape).get("mp", 1)) <= 1:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P

    def pin(x, kind):
        if kind == "heads":
            spec = P(*([None] * (x.ndim - 2)), "mp", None)
        else:   # "hidden_mp" / "ffn_mp"
            spec = P(*([None] * (x.ndim - 1)), "mp")
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    return pin


def decode_step_paged(params, tokens, cache, page_table, lengths,
                      config: GPTConfig, mesh=None):
    """Slot-indexed decode against the paged pool — ONE fixed-shape executable
    serves a churning request set (the continuous-batching hot loop).

    tokens [B] int32 — last emitted token per slot; cache {"k","v"}
    [L, P, page, KVH, hd]; page_table [B, max_pages] int32 page ids (0 = null
    page); lengths [B] int32 — tokens already cached per slot.  The new
    token's KV is written at position lengths[b] and attention masks each slot
    to its own lengths[b] + 1 positions.  Inactive slots (lengths 0, all-null
    table row) compute garbage the scheduler ignores.

    mesh (an 'mp' axis > 1) runs the step tensor-parallel: qkv/fc1 column- and
    proj/fc2 row-sharded (`parallel.hybrid.serving_param_specs`), the page
    pool sharded on its KVH axis (each chip holds num_heads/mp heads of every
    page), attention head-sharded per chip; page tables and lengths stay
    replicated host state.

    Returns (logits [B, V], updated cache).
    """
    from ..incubate.kernels.paged_attention import paged_attention_decode
    c = config
    assert c.causal, "KV-cache decoding requires a causal model"
    B = tokens.shape[0]
    page = cache["k"].shape[2]
    quant = "k_scale" in cache          # int8 pool: quantize writes in-program
    pos = lengths
    pin = serving_mp_constraint(mesh)
    parts = _mesh_mp(mesh)
    x = _embed(params, tokens, c, mesh=mesh)                 # [B, D]
    if not c.use_rope:
        x = x + jnp.take(params["wpe"], pos, axis=0)
    page_idx = jnp.take_along_axis(page_table, (pos // page)[:, None],
                                   axis=1)[:, 0]             # [B]
    offset = pos % page

    def layer(x, layer_in):
        bp, kv = layer_in                   # kv pool slices [P, page, KVH, hd]
        q, k, v = _decode_qkv(bp, x, c, pos, parts=parts)
        if pin:
            q, k, v = pin(q, "heads"), pin(k, "heads"), pin(v, "heads")
        if quant:
            k, ks = _quantize_kv(k)
            v, vs = _quantize_kv(v)
            kv = dict(kv, k_scale=kv["k_scale"].at[page_idx, offset].set(ks),
                      v_scale=kv["v_scale"].at[page_idx, offset].set(vs))
        kv = dict(kv, k=kv["k"].at[page_idx, offset].set(k),   # page scatter
                  v=kv["v"].at[page_idx, offset].set(v))
        attn = paged_attention_decode(q, kv["k"], kv["v"], page_table,
                                      pos + 1, mesh=mesh,
                                      kv_scales=_kv_scales(kv))
        x = _layer_tail(bp, x, attn.reshape(B, c.hidden_size), c, pin)
        return x, kv

    x, new_cache = jax.lax.scan(
        lambda carry, inp: layer(carry, inp), x, (params["blocks"], cache))
    x = epilogue(params, x, c)
    return head_logits(x, params, c, mesh=mesh), new_cache


def prefill_paged(params, input_ids, config: GPTConfig, cache, pages, length,
                  mesh=None):
    """Bucketed paged prefill: one dense causal pass over the bucket-padded
    prompt that writes KV into the slot's pages and returns logits at the last
    REAL position (right padding is sound under causal attention: position
    length-1 never attends to the padded tail).

    input_ids [B, Sb] right-padded to the bucket; pages [B, Sb // page_size]
    page ids (entries past the slot's reserved pages are the null page 0);
    length [B] int32 real prompt lengths.  Pool positions >= length hold
    padding garbage — masked by length during decode, overwritten as decode
    appends real tokens.  mesh: tensor-parallel over 'mp' (see
    `decode_step_paged`); the dense flash attention runs per-shard over the
    local head slice.  Returns (logits [B, V], cache).
    """
    c = config
    assert c.causal, "KV-cache decoding requires a causal model"
    B, Sb = input_ids.shape
    D, H, KVH, hd = c.hidden_size, c.num_heads, c.kv_heads, c.head_dim
    page = cache["k"].shape[2]
    n_chunks = Sb // page
    quant = "k_scale" in cache
    pin = serving_mp_constraint(mesh)
    parts = _mesh_mp(mesh)
    x = _embed(params, input_ids, c, mesh=mesh)
    if not c.use_rope:
        x = x + params["wpe"][:Sb]

    def attn_call(q, k, v):
        if pin is None:
            return flash_attention_fused(q, k, v, causal=True)
        # attention never mixes heads: run the (Pallas or XLA) flash body
        # per-shard on each chip's head slice — same trick as the paged lanes
        from ..incubate.kernels.paged_attention import _head_spec
        from ..parallel.ring_attention import shard_map_compat
        hs = _head_spec(4)
        return shard_map_compat(
            lambda a, b, d: flash_attention_fused(a, b, d, causal=True),
            mesh=mesh, axis_names={"mp"}, in_specs=(hs, hs, hs),
            out_specs=hs)(q, k, v)

    def layer(x, layer_in):
        bp, kv = layer_in
        q, k, v = _prefill_qkv(bp, x, c, parts=parts)
        if pin:
            q, k, v = pin(q, "heads"), pin(k, "heads"), pin(v, "heads")
        # the dense in-chunk attention below reads the FULL-precision k/v —
        # only the pool write quantizes, so a one-shot prompt's own logits
        # see zero KV quantization error (it lands on later readers)
        wk, wv = k, v
        if quant:
            wk, ks = _quantize_kv(k)
            wv, vs = _quantize_kv(v)
            kv = dict(
                kv,
                k_scale=kv["k_scale"].at[pages].set(
                    ks.reshape(B, n_chunks, page, KVH)),
                v_scale=kv["v_scale"].at[pages].set(
                    vs.reshape(B, n_chunks, page, KVH)))
        kv = dict(kv,
                  k=kv["k"].at[pages].set(wk.reshape(B, n_chunks, page, KVH,
                                                     hd)),
                  v=kv["v"].at[pages].set(wv.reshape(B, n_chunks, page, KVH,
                                                     hd)))
        if KVH != H:
            k = jnp.repeat(k, H // KVH, axis=2)
            v = jnp.repeat(v, H // KVH, axis=2)
        attn = attn_call(q, k, v).reshape(B, Sb, D)
        x = _layer_tail(bp, x, attn, c, pin)
        return x, kv

    x, new_cache = jax.lax.scan(
        lambda carry, inp: layer(carry, inp), x, (params["blocks"], cache))
    x = x[jnp.arange(B), length - 1]                 # last real position
    x = epilogue(params, x, c)
    return head_logits(x, params, c, mesh=mesh), new_cache


def _paged_chunk_hidden(params, input_ids, config: GPTConfig, cache,
                        page_table, q_offset, valid, attn_entry=None,
                        mesh=None):
    """Shared trunk of the q_offset-masked paged passes (`prefill_chunk_paged`
    and `verify_step_paged`): embed a [B, C] token chunk starting at per-slot
    absolute position q_offset, write its KV token-granularly at
    page_table[(q_offset+t) // page][(q_offset+t) % page] (padded tail rows
    t >= valid route to the reserved null page 0), and attend through the page
    table to everything already written below it.  attn_entry overrides the
    attention routing (the verify lane passes its own entry so lane-specific
    kernel behavior lands there, not here).  Returns (hidden states [B, C, D]
    BEFORE the final norm/head — callers pick their positions — and the
    updated cache)."""
    from ..incubate.kernels.paged_attention import paged_prefill_attention
    attn_fn = attn_entry or paged_prefill_attention
    c = config
    assert c.causal, "KV-cache decoding requires a causal model"
    B, C = input_ids.shape
    D = c.hidden_size
    page = cache["k"].shape[2]
    quant = "k_scale" in cache
    pin = serving_mp_constraint(mesh)
    parts = _mesh_mp(mesh)
    pos = q_offset[:, None] + jnp.arange(C)                  # [B, C]
    real = jnp.arange(C)[None, :] < valid[:, None]           # [B, C]
    x = _embed(params, input_ids, c, mesh=mesh)
    if not c.use_rope:
        # jnp.take clips padded-tail positions past wpe; their rows are junk
        # the scheduler never reads (rows >= valid are never consumed)
        x = x + jnp.take(params["wpe"], pos, axis=0)
    pidx = jnp.take_along_axis(page_table, pos // page, axis=1)
    pidx = jnp.where(real, pidx, 0)                          # pad -> null page
    off = pos % page

    def layer(x, layer_in):
        bp, kv = layer_in
        q, k, v = _prefill_qkv(bp, x, c, pos=pos, parts=parts)
        if pin:
            q, k, v = pin(q, "heads"), pin(k, "heads"), pin(v, "heads")
        if quant:
            k, ks = _quantize_kv(k)
            v, vs = _quantize_kv(v)
            kv = dict(kv, k_scale=kv["k_scale"].at[pidx, off].set(ks),
                      v_scale=kv["v_scale"].at[pidx, off].set(vs))
        kv = dict(kv, k=kv["k"].at[pidx, off].set(k),   # token-granular write
                  v=kv["v"].at[pidx, off].set(v))
        attn = attn_fn(q, kv["k"], kv["v"], page_table, q_offset, valid,
                       mesh=mesh, kv_scales=_kv_scales(kv))
        x = _layer_tail(bp, x, attn.reshape(B, C, D), c, pin)
        return x, kv

    x, new_cache = jax.lax.scan(
        lambda carry, inp: layer(carry, inp), x, (params["blocks"], cache))
    return x, new_cache


def prefill_chunk_paged(params, input_ids, config: GPTConfig, cache,
                        page_table, q_offset, valid, mesh=None):
    """Chunked paged prefill (Sarathi-style, Agrawal et al. OSDI 2024): one
    dense pass over a fixed-size chunk of the prompt starting at position
    q_offset, attending through the page table to everything already written
    below it (prefix-cached pages and earlier chunks).  ONE compiled
    executable serves every chunk of every prompt — q_offset, valid and the
    page ids are all data, not shape.

    input_ids [B, C] right-padded chunk; page_table [B, max_pages] the slot's
    FULL table row; q_offset [B] int32 absolute position of input_ids[:, 0];
    valid [B] int32 real tokens in the chunk (>= 1).  KV is written
    token-granularly at page_table[(q_offset+t) // page][(q_offset+t) % page]
    — unlike the bucketed `prefill_paged`'s whole-page writes, this never
    clobbers the head of a copy-on-write page the chunk starts inside, and
    padded tail tokens route to the reserved null page 0.  Returns
    (logits [B, V] at chunk index valid-1 — the caller uses them only for the
    final chunk — and the updated cache).
    """
    B = input_ids.shape[0]
    x, cache = _paged_chunk_hidden(params, input_ids, config, cache,
                                   page_table, q_offset, valid, mesh=mesh)
    x = x[jnp.arange(B), valid - 1]                  # last real chunk position
    x = epilogue(params, x, config)
    return head_logits(x, params, config, mesh=mesh), cache


def verify_step_paged(params, tokens, cache, page_table, lengths, valid,
                      config: GPTConfig, mesh=None):
    """Speculative-decode verify (Leviathan et al. 2023): score spec_len + 1
    positions per slot in ONE fixed-shape executable — the multi-token sibling
    of `decode_step_paged`, riding the same q_offset-masked paged attention as
    `prefill_chunk_paged`.

    tokens [B, T] int32 (T = spec_len + 1): tokens[:, 0] is the slot's last
    emitted token (exactly what vanilla decode would be fed), tokens[:, 1:]
    the drafted continuation; token t sits at absolute position lengths[b] + t.
    lengths [B] int32 — tokens already cached per slot (the verify analogue of
    decode's per-slot position); valid [B] int32 in [1, T] — real tokens per
    slot (1 = no draft, plain decode through the verify program).  Candidate
    KV is written token-granularly into the slot's reserved pages (rows
    t >= valid route to the null page); the caller rolls rejected positions
    back by NOT advancing lengths past the accepted prefix — the stale KV is
    overwritten when decode reaches those positions again.

    Returns (logits [B, T, V] at EVERY position — logits[b, t] predicts the
    token after tokens[b, t], so greedy acceptance compares argmax(logits[:, t])
    against tokens[:, t+1] and argmax(logits[:, a]) is the bonus token — and
    the updated cache).
    """
    from ..incubate.kernels.paged_attention import paged_verify_attention
    x, cache = _paged_chunk_hidden(params, tokens, config, cache,
                                   page_table, lengths, valid,
                                   attn_entry=paged_verify_attention,
                                   mesh=mesh)
    x = epilogue(params, x, config)
    return head_logits(x, params, config, mesh=mesh), cache


def serve_step_paged(params, tokens, cache, page_table, q_offset, valid,
                     config: GPTConfig, key=None, greedy=None, *,
                     sample: bool = False, temperature=1.0, top_k=None,
                     mesh=None):
    """The fused serving step: decode, spec-verify and an interleaved prefill
    chunk ride ONE fixed-shape executable, and sampling + greedy acceptance
    run on device — the host fetches a small `[B, T] + [B]` int token/accept
    buffer instead of `[B, V]` logits (the reference's single-graph
    `AnalysisPredictor::ZeroCopyRun` step, Sarathi-style piggybacking).

    Per-slot contract (mode is implied by the scheduler's inputs, not a
    device lane):
    - decode slot:  tokens[b, 0] = last emitted token, valid[b] = 1,
      q_offset[b] = tokens already cached;
    - verify slot:  tokens[b, 1:1+K] = drafted continuation, valid[b] = 1+K
      (`verify_step_paged` semantics — rejected KV rolls back as a length
      decrement on the host);
    - chunk slot:   tokens[b, :n] = the next prompt chunk, valid[b] = n,
      q_offset[b] = prompt tokens already in pages (`prefill_chunk_paged`
      semantics — only the final chunk's pick is consumed);
    - inactive:     null page-table row, valid[b] = 1 (garbage the scheduler
      ignores).

    Returns (out_tokens [B, T] int32, accept [B] int32, cache, key):
    `out_tokens[b, t]` is the greedy prediction after position t, except
    position valid-1 where sampled (greedy[b]=False) slots carry the
    temperature/top-k pick instead — so a decode slot's token is
    `out[b, 0]`, a finished chunk's first token is `out[b, valid-1]`, and a
    verify slot emits `out[b, :accept[b]+1]` (accepted drafted prefix, which
    equals the predictions it matched, plus the bonus token).  `accept[b]` is
    the on-device greedy longest-prefix match length over the drafted tokens
    (0 for undrafted slots).  `key` advances by one split iff `sample`.
    """
    from ..incubate.kernels.paged_attention import paged_serve_attention
    x, cache = _paged_chunk_hidden(params, tokens, config, cache,
                                   page_table, q_offset, valid,
                                   attn_entry=paged_serve_attention,
                                   mesh=mesh)
    x = epilogue(params, x, config)
    logits = head_logits(x, params, config, mesh=mesh)  # [B, T, V] (V/mp ea.)
    out = sharded_argmax(logits, mesh)                        # [B, T]
    B, T = tokens.shape
    rows = jnp.arange(B)
    if sample:
        # one batched pick at each slot's last real position, through the ONE
        # shared sampling implementation (`sample_token` split-key
        # discipline); the greedy mask routes temperature=0.0 requests to the
        # argmax already in `out`, so their tokens stay PRNG-independent
        ids, key = sample_token(logits[rows, valid - 1], key, sample=True,
                                temperature=temperature, top_k=top_k,
                                mesh=mesh)
        pick = jnp.where(greedy, out[rows, valid - 1], ids)
        out = out.at[rows, valid - 1].set(pick)
    # greedy longest-prefix acceptance, on device: drafted token t+1 is
    # accepted iff it equals the prediction after position t and every
    # earlier draft was accepted (cumprod); positions past the draft
    # (t >= valid-1) never match.  Sampled slots carry no draft (valid=1),
    # so the fold at valid-1 above cannot perturb the scan.
    match = (tokens[:, 1:] == out[:, :-1]) & \
        (jnp.arange(T - 1)[None, :] < (valid - 1)[:, None])
    accept = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                     axis=1).astype(jnp.int32)
    return out, accept, cache, key


def swap_out_pages(cache, page_ids):
    """Preemption swap-out gather (vLLM-style KV swapping): copy the victim's
    pages out of the pool into a standalone device buffer the host then
    fetches at its leisure — the gather is a fresh buffer, so the pool pages
    can be handed to a new owner immediately and the d2h overlaps the next
    decode dispatch.

    cache {"k","v"} [L, P, page, KVH, hd]; page_ids [max_pages] int32 — the
    victim's pages PADDED to the slot capacity with the null page 0, so ONE
    fixed-shape executable serves every victim (padding rows carry null-page
    garbage the host discards).  Returns {"k","v"} [L, max_pages, page, KVH,
    hd]."""
    return {n: a[:, page_ids] for n, a in cache.items()}


def swap_in_pages(cache, page_ids, data):
    """Preemption swap-in scatter: restore a previously swapped victim's KV
    into its freshly allocated pages.  page_ids is padded with the null page
    0 exactly like `swap_out_pages` — padding rows scatter zeros into page 0,
    which is written by every inactive slot anyway and never read.  `data`
    is the pool-keyed staging dict (`{"k", "v"}`, plus the scale lanes on a
    quantized pool — int8 pages swap as int8, which is what halves the
    JXP009 host-pool pressure).  The pool arrives donated (in-place
    restore); returns the updated cache."""
    return {n: a.at[:, page_ids].set(data[n]) for n, a in cache.items()}


# LRU-bounded executable cache for `generate` (unbounded it leaks one compiled
# program per (config, B, Tp, max_new, sampling) combination — a real leak
# under varied prompt shapes; the serving engine bounds shapes by bucketing
# instead, see inference/engine.py).
GENERATE_CACHE_MAX = 16
_generate_cache: "OrderedDict[Any, Any]" = OrderedDict()
_generate_compiles = 0


def generate_cache_stats():
    """{'size', 'compiles', 'max_size'} — benches/tests assert on `compiles`
    to catch shape-churn recompilation regressions."""
    return {"size": len(_generate_cache), "compiles": _generate_compiles,
            "max_size": GENERATE_CACHE_MAX}


def sample_token(logits, key, *, sample, temperature, top_k, mesh=None):
    """Greedy argmax or temperature/top-k sample over [B, V] logits.

    The ONE sampling implementation shared by `generate` and the serving
    engine (`inference.engine.LLMEngine`) so their outputs cannot drift.
    `temperature` may be a traced scalar.  Returns (ids [B] int32, key).

    The categorical draw is written as the gumbel-argmax identity
    (`categorical(key, lg) == argmax(lg + gumbel(key, lg.shape))` — the same
    construction jax.random.categorical uses) so the mp1 and vocab-sharded
    paths are the SAME math on the same noise: under an mp mesh (logits
    arrive [.., V/mp]-sharded from `head_logits`) the full-width noise is
    deterministic per (key, element) regardless of sharding, each chip adds
    the slice it owns, a top-k threshold merges per-chip local top-ks (one
    k·mp-scalar all-gather per row — never the logits), and the pick is the
    deterministic (value, global index) merge of `sharded_argmax`.  Fixed
    key ⇒ byte-identical ids across mp∈{1,2,4} by construction."""
    if sample:
        key, sub = jax.random.split(key)
        lg = logits / temperature
        noise = jax.random.gumbel(sub, lg.shape, lg.dtype)
        if _mesh_mp(mesh) <= 1:
            if top_k:
                kth = jax.lax.top_k(lg, top_k)[0][:, -1:]
                lg = jnp.where(lg < kth, -1e30, lg)
            return jnp.argmax(lg + noise, axis=-1).astype(jnp.int32), key

        from jax.sharding import PartitionSpec as P
        from ..parallel.ring_attention import shard_map_compat
        V = lg.shape[-1]
        kk = int(top_k) if top_k else 0

        def local(lg_l, nz_l):
            r = jax.lax.axis_index("mp")
            Vl = lg_l.shape[-1]
            if kk:
                mine = jax.lax.top_k(lg_l, min(kk, Vl))[0]
                allk = jax.lax.all_gather(mine, "mp", axis=-1, tiled=True)
                kth = jax.lax.top_k(allk, kk)[0][:, -1:]
                lg_l = jnp.where(lg_l < kth, -1e30, lg_l)
            g = lg_l + nz_l
            lv = jnp.max(g, axis=-1)
            li = jnp.argmax(g, axis=-1).astype(jnp.int32) + r * Vl
            gm = jax.lax.pmax(lv, "mp")
            cand = jnp.where(lv == gm, li, V)
            return jax.lax.pmin(cand, "mp").astype(jnp.int32)

        ids = shard_map_compat(
            local, mesh=mesh, axis_names={"mp"},
            in_specs=(P(None, "mp"), P(None, "mp")), out_specs=P())(lg, noise)
        return ids, key
    return sharded_argmax(logits, mesh), key


def generate(params, input_ids, config: GPTConfig, max_new_tokens: int = 32,
             temperature: float = 0.0, top_k: Optional[int] = None,
             eos_token_id: Optional[int] = None, key=None):
    """Greedy / temperature sampling with a KV cache: one batched prefill
    pass, then a decode lax.scan — the WHOLE loop is one cached jitted
    program (repeat calls with the same shapes reuse the executable).
    Sequences that emit eos_token_id are frozen at EOS from then on.

    input_ids [B, T_prompt] int32 -> [B, T_prompt + max_new_tokens].
    """
    B, Tp = input_ids.shape
    total = Tp + max_new_tokens
    if not config.use_rope and total > config.max_seq_len:
        raise ValueError(
            f"prompt {Tp} + max_new_tokens {max_new_tokens} exceeds "
            f"max_seq_len {config.max_seq_len} (learned positions)")
    if key is None:
        key = jax.random.key(0)
    sample = bool(temperature and temperature > 0.0)

    cache_key = (dataclasses.astuple(config), B, Tp, max_new_tokens,
                 sample, top_k, eos_token_id)
    fn = _generate_cache.get(cache_key)
    if fn is not None:
        _generate_cache.move_to_end(cache_key)      # LRU touch
    else:
        def impl(params, ids, temp, key):
            kv = init_cache(config, B, total)

            def pick(logits, key_):
                return sample_token(logits, key_, sample=sample,
                                    temperature=temp, top_k=top_k)

            logits, kv = prefill(params, ids, config, kv)
            first, key = pick(logits, key)
            finished0 = (first == eos_token_id) if eos_token_id is not None \
                else jnp.zeros((B,), bool)
            tokens = jnp.concatenate(
                [ids, first[:, None],
                 jnp.zeros((B, max_new_tokens - 1), jnp.int32)], axis=1)

            def step(carry, pos):
                tokens, kv, key_, finished = carry
                tok = jax.lax.dynamic_index_in_dim(tokens, pos, axis=1,
                                                   keepdims=False)
                logits, kv = decode_step(params, tok, kv, pos, config)
                nxt, key_ = pick(logits, key_)
                if eos_token_id is not None:
                    nxt = jnp.where(finished, eos_token_id, nxt)
                    finished = finished | (nxt == eos_token_id)
                tokens = jax.lax.dynamic_update_slice_in_dim(
                    tokens, nxt[:, None], pos + 1, axis=1)
                return (tokens, kv, key_, finished), None

            if max_new_tokens > 1:
                (tokens, _, _, _), _ = jax.lax.scan(
                    step, (tokens, kv, key, finished0),
                    jnp.arange(Tp, total - 1))
            return tokens

        # tpu-lint: disable=TPL003 -- params are REUSED across generate() calls (the executable is LRU-cached); donating them would invalidate the caller's buffers
        fn = jax.jit(impl)
        global _generate_compiles
        _generate_compiles += 1
        _generate_cache[cache_key] = fn
        while len(_generate_cache) > GENERATE_CACHE_MAX:
            _generate_cache.popitem(last=False)     # evict least-recently-used
    return fn(params, jnp.asarray(input_ids, jnp.int32),
              jnp.asarray(temperature if sample else 1.0, jnp.float32), key)
