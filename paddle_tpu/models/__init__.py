from . import gpt  # noqa
from .gpt import GPTConfig, GPTForCausalLM, gpt3_1p3b, gpt_tiny  # noqa
