from . import creation, einsum, linalg, logic, manipulation, math, random, search, stat  # noqa
