"""Math ops (reference: `python/paddle/tensor/math.py`, `ops.py`).

Every function dispatches through `core.tensor.apply`, so it records on the autograd tape
and autocasts under AMP exactly like a generated ad_func in the reference.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core.tensor import Tensor, apply, _to_data


def _unary(name, jfn):
    def op(x, name=None):
        return apply(name_, jfn, x)
    name_ = name
    op.__name__ = name
    return op


def _binary(name, jfn):
    def op(x, y, name=None):
        return apply(name_, jfn, x, y)
    name_ = name
    op.__name__ = name
    return op


# ---- unary elementwise ----
abs = _unary("abs", jnp.abs)
acos = _unary("acos", jnp.arccos)
acosh = _unary("acosh", jnp.arccosh)
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
ceil = _unary("ceil", jnp.ceil)
conj = _unary("conj", jnp.conj)
cos = _unary("cos", jnp.cos)
cosh = _unary("cosh", jnp.cosh)
digamma = _unary("digamma", jax.scipy.special.digamma)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
floor = _unary("floor", jnp.floor)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
i0 = _unary("i0", jax.scipy.special.i0)
i0e = _unary("i0e", jax.scipy.special.i0e)
i1 = _unary("i1", jax.scipy.special.i1)
i1e = _unary("i1e", jax.scipy.special.i1e)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
log = _unary("log", jnp.log)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
log2 = _unary("log2", jnp.log2)
neg = _unary("neg", jnp.negative)
reciprocal = _unary("reciprocal", jnp.reciprocal)
round = _unary("round", jnp.round)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
sgn = _unary("sgn", jnp.sign)
sign = _unary("sign", jnp.sign)
sin = _unary("sin", jnp.sin)
sinh = _unary("sinh", jnp.sinh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
tan = _unary("tan", jnp.tan)
tanh = _unary("tanh", jnp.tanh)
trunc = _unary("trunc", jnp.trunc)
angle = _unary("angle", jnp.angle)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
isfinite = _unary("isfinite", jnp.isfinite)
isinf = _unary("isinf", jnp.isinf)
isnan = _unary("isnan", jnp.isnan)
isneginf = _unary("isneginf", jnp.isneginf)
isposinf = _unary("isposinf", jnp.isposinf)
isreal = _unary("isreal", jnp.isreal)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
exponential_ = None  # random module provides

# ---- binary elementwise ----
add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.true_divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
mod = _binary("remainder", jnp.remainder)
remainder = mod
floor_mod = mod
pow = _binary("pow", jnp.power)
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
logaddexp = _binary("logaddexp", jnp.logaddexp)
hypot = _binary("hypot", lambda x, y: jnp.sqrt(x * x + y * y))
copysign = _binary("copysign", jnp.copysign)
nextafter = _binary("nextafter", jnp.nextafter)
ldexp = _binary("ldexp", lambda x, y: x * jnp.power(2.0, y).astype(x.dtype) if jnp.issubdtype(x.dtype, jnp.floating) else (x * (2 ** y)))
gammaln = lgamma
polygamma = lambda x, n, name=None: apply("polygamma", lambda a: jax.scipy.special.polygamma(n, a), x)
heaviside = _binary("heaviside", lambda x, y: jnp.where(x < 0, 0.0, jnp.where(x > 0, 1.0, y)).astype(x.dtype))
inner = _binary("inner", jnp.inner)
outer = _binary("outer", lambda x, y: jnp.outer(x, y))
kron = _binary("kron", jnp.kron)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale.item() if isinstance(scale, Tensor) else scale
    if bias_after_scale:
        out = apply("scale", lambda a: a * s + bias, x)
    else:
        out = apply("scale", lambda a: (a + bias) * s, x)
    return out


def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply("clip", lambda a: jnp.clip(a, lo, hi), x)


def lerp(x, y, weight, name=None):
    if isinstance(weight, (int, float)):
        return apply("lerp", lambda a, b: a + weight * (b - a), x, y)
    return apply("lerp", lambda a, b, w: a + w * (b - a), x, y, weight)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), x)


def multiplex(inputs, index, name=None):
    def f(idx, *ins):
        stacked = jnp.stack(ins, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape((1, -1) + (1,) * (stacked.ndim - 2)).astype(jnp.int32),
            axis=0)[0]
    return apply("multiplex", f, index, *inputs)


# ---- matmul family ----
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply("matmul", f, x, y)


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return apply("bmm", jnp.matmul, x, y)


def dot(x, y, name=None):
    return apply("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def mv(x, vec, name=None):
    return apply("mv", jnp.matmul, x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply("addmm", lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), input, x, y)


def t(x, name=None):
    return apply("t", lambda a: a.T if a.ndim == 2 else a, x)


# ---- reductions ----
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        ax = np.asarray(axis._data)
        return tuple(int(a) for a in np.atleast_1d(ax))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _axis(axis)
    npd = _dt.to_np(dtype) if dtype is not None else None

    def f(a):
        out_dtype = npd
        if out_dtype is None and jnp.issubdtype(a.dtype, jnp.bool_):
            out_dtype = jnp.int64
        return jnp.sum(a, axis=ax, keepdims=keepdim, dtype=out_dtype)
    return apply("sum", f, x)


def mean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("mean", lambda a: jnp.mean(a, axis=ax, keepdims=keepdim), x)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    ax = _axis(axis)
    npd = _dt.to_np(dtype) if dtype is not None else None
    return apply("prod", lambda a: jnp.prod(a, axis=ax, keepdims=keepdim, dtype=npd), x)


def max(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("max", lambda a: jnp.max(a, axis=ax, keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("min", lambda a: jnp.min(a, axis=ax, keepdims=keepdim), x)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("logsumexp", lambda a: jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim), x)


def all(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("all", lambda a: jnp.all(a, axis=ax, keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("any", lambda a: jnp.any(a, axis=ax, keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("count_nonzero", lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim), x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _axis(axis)
    npd = _dt.to_np(dtype) if dtype is not None else None
    return apply("nansum", lambda a: jnp.nansum(a, axis=ax, keepdims=keepdim, dtype=npd), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("nanmean", lambda a: jnp.nanmean(a, axis=ax, keepdims=keepdim), x)


# ---- cumulative ----
def cumsum(x, axis=None, dtype=None, name=None):
    npd = _dt.to_np(dtype) if dtype is not None else None

    def f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=npd)
        return jnp.cumsum(a, axis=int(axis), dtype=npd)
    return apply("cumsum", f, x)


def cumprod(x, dim=None, dtype=None, name=None):
    npd = _dt.to_np(dtype) if dtype is not None else None
    return apply("cumprod", lambda a: jnp.cumprod(a, axis=dim, dtype=npd), x)


def cummax(x, axis=None, dtype="int64", name=None):
    def f(a):
        ax = axis if axis is not None else 0
        arr = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.maximum, arr, axis=ax if axis is not None else 0)
        n = arr.shape[ax if axis is not None else 0]
        eq = arr == vals
        idx = jnp.arange(n).reshape([-1 if i == (ax % arr.ndim) else 1 for i in range(arr.ndim)])
        inds = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, idx, -1), axis=ax)
        return vals, inds.astype(_dt.to_np(dtype))
    return apply("cummax", f, x)


def cummin(x, axis=None, dtype="int64", name=None):
    def f(a):
        ax = axis if axis is not None else 0
        arr = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.minimum, arr, axis=ax)
        n = arr.shape[ax]
        eq = arr == vals
        idx = jnp.arange(n).reshape([-1 if i == (ax % arr.ndim) else 1 for i in range(arr.ndim)])
        inds = jax.lax.associative_scan(jnp.maximum, jnp.where(eq, idx, -1), axis=ax)
        return vals, inds.astype(_dt.to_np(dtype))
    return apply("cummin", f, x)


def logcumsumexp(x, axis=None, name=None):
    def f(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        return jax.lax.associative_scan(jnp.logaddexp, arr, axis=ax)
    return apply("logcumsumexp", f, x)


# ---- misc ----
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("trace", lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("diagonal", lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2), x)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = _to_data(prepend) if prepend is not None else None
    app = _to_data(append) if append is not None else None
    return apply("diff", lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app), x)


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else None
    def f(a, b):
        use_ax = ax
        if use_ax is None:
            for i, s in enumerate(a.shape):
                if s == 3:
                    use_ax = i
                    break
        return jnp.cross(a, b, axis=use_ax)
    return apply("cross", f, x, y)


def gcd(x, y, name=None):
    return apply("gcd", jnp.gcd, x, y)


def lcm(x, y, name=None):
    return apply("lcm", jnp.lcm, x, y)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply("nan_to_num", lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x)


def take(x, index, mode="raise", name=None):
    def f(a, idx):
        flat = a.reshape(-1)
        n = flat.shape[0]
        ii = idx.astype(jnp.int64)
        if mode == "wrap":
            ii = jnp.mod(ii, n)
        else:
            ii = jnp.clip(jnp.where(ii < 0, ii + n, ii), 0, n - 1)
        return flat[ii]
    return apply("take", f, x, index)


def increment(x, value=1.0, name=None):
    x._data = x._data + value
    return x


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    def f(pred, lab):
        topk = jnp.argsort(-pred, axis=-1)[:, :k]
        match = jnp.any(topk == lab.reshape(-1, 1), axis=-1)
        return jnp.mean(match.astype(jnp.float32))
    return apply("accuracy", f, input, label)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# ---- breadth additions (reference python/paddle/tensor/math.py) ----

sigmoid = _unary("sigmoid", jax.nn.sigmoid)


def logit(x, eps=None, name=None):
    """ref `tensor/math.py` logit: log(p/(1-p)) with optional eps clamp."""
    def f(a):
        p = a if eps is None else jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(p) - jnp.log1p(-p)
    return apply("logit", f, x)


def add_n(inputs, name=None):
    """Sum a list of same-shape tensors (ref `sum` op / add_n)."""
    if isinstance(inputs, Tensor):
        return inputs
    return apply("add_n", lambda *ts: functools.reduce(jnp.add, ts), *inputs)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal rule integral (ref tensor/math.py trapezoid)."""
    if x is not None:
        return apply("trapezoid", lambda yy, xx: jnp.trapezoid(yy, xx, axis=axis),
                     y, x)
    d = 1.0 if dx is None else dx
    return apply("trapezoid", lambda yy: jnp.trapezoid(yy, dx=d, axis=axis), y)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Cumulative trapezoidal integral (ref tensor/math.py)."""
    def cum(yy, spacing):
        a = jnp.moveaxis(yy, axis, -1)
        avg = (a[..., 1:] + a[..., :-1]) / 2.0
        seg = avg * spacing
        return jnp.moveaxis(jnp.cumsum(seg, axis=-1), -1, axis)

    if x is not None:
        def f(yy, xx):
            xs = jnp.moveaxis(xx, axis, -1) if xx.ndim == yy.ndim else xx
            d = jnp.diff(xs, axis=-1) if xs.ndim > 1 or xx.ndim == yy.ndim \
                else jnp.diff(xs)
            return cum(yy, d)
        return apply("cumulative_trapezoid", f, y, x)
    d = 1.0 if dx is None else dx
    return apply("cumulative_trapezoid", lambda yy: cum(yy, d), y)


def frexp(x, name=None):
    """Decompose x = m * 2**e with 0.5 <= |m| < 1 (ref tensor/math.py frexp)."""
    def f(a):
        zero = a == 0
        e = jnp.where(zero, 0, jnp.floor(jnp.log2(jnp.abs(jnp.where(zero, 1.0, a)))) + 1)
        m = jnp.where(zero, 0.0, a / jnp.exp2(e))
        # normalize edge cases where |m| == 1 (log2 exactness)
        fix = jnp.abs(m) >= 1.0
        e = jnp.where(fix, e + 1, e)
        m = jnp.where(fix, m / 2, m)
        return m, e.astype(a.dtype)
    return apply("frexp", f, x)


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize sub-tensors along axis to p-norm <= max_norm (ref renorm op)."""
    def f(a):
        red = tuple(i for i in range(a.ndim) if i != (axis % a.ndim))
        norms = jnp.sum(jnp.abs(a) ** p, axis=red, keepdims=True) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return a * scale
    return apply("renorm", f, x)
