"""Random ops (reference: `python/paddle/tensor/random.py`).

Statefulness: eager random ops draw from the default `Generator` (core/generator.py),
which splits a fresh jax PRNG subkey per call — matching the reference's global-seeded
Philox behaviour.  Inside `to_static`/jit the RNG key is captured as explicit state.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core import generator as _gen
from ..core.tensor import Tensor, apply, _to_data
from .creation import _shape


def _npd(dtype, default=_dt.float32):
    return _dt.to_np(dtype) if dtype is not None else _dt.to_np(_dt._default_dtype if default is None else default)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else _gen.next_key()
    d = _npd(dtype, None)
    return Tensor(jax.random.uniform(key, _shape(shape), d, minval=min, maxval=max))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def standard_normal(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(_gen.next_key(), _shape(shape), _npd(dtype, None)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = _to_data(mean)
        s = _to_data(std)
        out_shape = np.broadcast_shapes(np.shape(m), np.shape(s))
        z = jax.random.normal(_gen.next_key(), out_shape, jnp.float32)
        return Tensor(m + s * z)
    z = jax.random.normal(_gen.next_key(), _shape(shape or [1]), _npd(None, None))
    return Tensor(mean + std * z)


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = jax.random.key(seed) if seed else _gen.next_key()
    z = jax.random.normal(key, _shape(shape), _npd(dtype, None))
    return Tensor(mean + std * z)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = _dt.to_np(dtype) if dtype is not None else np.int64
    return Tensor(jax.random.randint(_gen.next_key(), _shape(shape), low, high, d))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    data = _to_data(x)
    if high is None:
        low, high = 0, low
    d = _dt.to_np(dtype) if dtype is not None else data.dtype
    out = jax.random.randint(_gen.next_key(), data.shape, low, high,
                             d if np.issubdtype(d, np.integer) else np.int64)
    return Tensor(out.astype(d))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(_gen.next_key(), int(n)).astype(_dt.to_np(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    data = _to_data(x)
    key = _gen.next_key()
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=(num_samples,) + data.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, data.shape)
        out = jnp.argsort(-(logits + g), axis=-1)[..., :num_samples]
    return Tensor(out.astype(jnp.int64))


def bernoulli(x, name=None):
    data = _to_data(x)
    u = jax.random.uniform(_gen.next_key(), data.shape, data.dtype)
    return Tensor((u < data).astype(data.dtype))


def bernoulli_(x, p=0.5, name=None):
    u = jax.random.uniform(_gen.next_key(), x._data.shape)
    x._data = (u < p).astype(x._data.dtype)
    return x


def poisson(x, name=None):
    data = _to_data(x)
    return Tensor(jax.random.poisson(_gen.next_key(), data, data.shape).astype(data.dtype))


def binomial(count, prob, name=None):
    c = _to_data(count)
    p = _to_data(prob)
    return Tensor(jax.random.binomial(_gen.next_key(), c.astype(jnp.float32),
                                      p.astype(jnp.float32)).astype(jnp.int64))


def exponential_(x, lam=1.0, name=None):
    u = jax.random.uniform(_gen.next_key(), x._data.shape, x._data.dtype if
                           jnp.issubdtype(x._data.dtype, jnp.floating) else jnp.float32)
    x._data = (-jnp.log1p(-u) / lam).astype(x._data.dtype)
    return x


def cauchy_(x, loc=0, scale=1, name=None):
    key = _gen.next_key()
    x._data = (loc + scale * jax.random.cauchy(key, x._data.shape)).astype(x._data.dtype)
    return x


def geometric_(x, probs, name=None):
    u = jax.random.uniform(_gen.next_key(), x._data.shape)
    x._data = (jnp.ceil(jnp.log(u) / jnp.log1p(-probs))).astype(x._data.dtype)
    return x


def log_normal_(x, mean=1.0, std=2.0, name=None):
    z = jax.random.normal(_gen.next_key(), x._data.shape)
    x._data = jnp.exp(mean + std * z).astype(x._data.dtype)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    z = jax.random.normal(_gen.next_key(), x._data.shape)
    x._data = (mean + std * z).astype(x._data.dtype)
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else _gen.next_key()
    x._data = jax.random.uniform(key, x._data.shape, x._data.dtype, min, max)
    return x


def rand_like(x, dtype=None, name=None):
    data = _to_data(x)
    d = _dt.to_np(dtype) if dtype is not None else data.dtype
    return Tensor(jax.random.uniform(_gen.next_key(), data.shape, d))


def randn_like(x, dtype=None, name=None):
    data = _to_data(x)
    d = _dt.to_np(dtype) if dtype is not None else data.dtype
    return Tensor(jax.random.normal(_gen.next_key(), data.shape, d))


def get_rng_state():
    return [_gen.default_generator().get_state()]


def set_rng_state(state):
    _gen.default_generator().set_state(state[0] if isinstance(state, (list, tuple)) else state)


def get_cuda_rng_state():
    return get_rng_state()


def set_cuda_rng_state(state):
    set_rng_state(state)
