"""Shape/layout manipulation ops (reference: `python/paddle/tensor/manipulation.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core.tensor import Tensor, apply, _to_data


def _ints(v):
    if isinstance(v, Tensor):
        return tuple(int(x) for x in np.atleast_1d(np.asarray(v._data)))
    if isinstance(v, (int, np.integer)):
        return (int(v),)
    return tuple(int(x._data) if isinstance(x, Tensor) else int(x) for x in v)


def reshape(x, shape, name=None):
    s = _ints(shape)
    return apply("reshape", lambda a: jnp.reshape(a, s), x)


def reshape_(x, shape, name=None):
    return x._inplace_from(reshape(x, shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        st = start_axis % nd if nd else 0
        sp = stop_axis % nd if nd else 0
        new_shape = a.shape[:st] + (-1,) + a.shape[sp + 1:]
        return jnp.reshape(a, new_shape)
    return apply("flatten", f, x)


def squeeze(x, axis=None, name=None):
    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = _ints(axis)
        axes = tuple(ax % a.ndim for ax in axes if a.shape[ax % a.ndim] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a
    return apply("squeeze", f, x)


def squeeze_(x, axis=None, name=None):
    return x._inplace_from(squeeze(x, axis))


def unsqueeze(x, axis, name=None):
    axes = _ints(axis)
    return apply("unsqueeze", lambda a: jnp.expand_dims(a, axes), x)


def unsqueeze_(x, axis, name=None):
    return x._inplace_from(unsqueeze(x, axis))


def transpose(x, perm, name=None):
    p = _ints(perm)
    return apply("transpose", lambda a: jnp.transpose(a, p), x)


def moveaxis(x, source, destination, name=None):
    return apply("moveaxis", lambda a: jnp.moveaxis(a, _ints(source), _ints(destination)), x)


def swapaxes(x, axis1, axis2, name=None):
    return apply("swapaxes", lambda a: jnp.swapaxes(a, int(axis1), int(axis2)), x)


transpose_ = None
concat_list = None


def concat(x, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    tensors = list(x)
    return apply("concat", lambda *arrs: jnp.concatenate(arrs, axis=ax), *tensors)


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply("stack", lambda *arrs: jnp.stack(arrs, axis=axis), *tensors)


def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else x.shape[axis]
    outs = apply("unstack", lambda a: tuple(jnp.moveaxis(a, axis, 0)[i] for i in range(n)), x)
    return list(outs)


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)

    def f(a):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=ax))
        secs = [int(s._data) if isinstance(s, Tensor) else int(s) for s in num_or_sections]
        total = a.shape[ax]
        known = builtins_sum(s for s in secs if s >= 0)
        secs = [s if s >= 0 else total - known for s in secs]
        idx = np.cumsum(secs)[:-1].tolist()
        return tuple(jnp.split(a, idx, axis=ax))
    outs = apply("split", f, x)
    return list(outs)


import builtins
builtins_sum = builtins.sum


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tensor_split(x, num_or_indices, axis=0, name=None):
    def f(a):
        return tuple(jnp.array_split(a, num_or_indices, axis=axis)) if isinstance(num_or_indices, int) \
            else tuple(jnp.split(a, list(num_or_indices), axis=axis))
    return list(apply("tensor_split", f, x))


def tile(x, repeat_times, name=None):
    reps = _ints(repeat_times)
    return apply("tile", lambda a: jnp.tile(a, reps), x)


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats if isinstance(repeats, int) else _to_data(repeats)
    return apply("repeat_interleave", lambda a: jnp.repeat(a, r, axis=axis), x)


def expand(x, shape, name=None):
    s = _ints(shape)

    def f(a):
        tgt = list(s)
        src = list(a.shape)
        src = [1] * (len(tgt) - len(src)) + src
        tgt = [src[i] if tgt[i] == -1 else tgt[i] for i in range(len(tgt))]
        return jnp.broadcast_to(a.reshape(src), tgt)
    return apply("expand", f, x)


def expand_as(x, y, name=None):
    return apply("expand_as", lambda a, b: jnp.broadcast_to(a, b.shape), x, y)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    outs = apply("broadcast_tensors", lambda *arrs: tuple(jnp.broadcast_arrays(*arrs)), *inputs)
    return list(outs)


def flip(x, axis, name=None):
    axes = _ints(axis)
    return apply("flip", lambda a: jnp.flip(a, axis=axes), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


def roll(x, shifts, axis=None, name=None):
    sh = _ints(shifts) if not isinstance(shifts, int) else shifts
    ax = _ints(axis) if axis is not None and not isinstance(axis, int) else axis
    return apply("roll", lambda a: jnp.roll(a, sh, axis=ax), x)


def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply("gather", lambda a, idx: jnp.take(a, idx.astype(jnp.int32), axis=ax), x, index)


def gather_nd(x, index, name=None):
    def f(a, idx):
        ii = tuple(jnp.moveaxis(idx.astype(jnp.int32), -1, 0))
        return a[ii]
    return apply("gather_nd", f, x, index)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply("take_along_axis",
                 lambda a, idx: jnp.take_along_axis(a, idx.astype(jnp.int64), axis=axis),
                 arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True, name=None):
    def f(a, idx, v):
        idx = idx.astype(jnp.int64)
        v = jnp.broadcast_to(v, idx.shape).astype(a.dtype)
        dims = list(range(a.ndim))
        grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        full_idx = [grids[d] for d in dims]
        full_idx[axis] = idx
        if reduce == "assign":
            return a.at[tuple(full_idx)].set(v)
        if reduce == "add":
            return a.at[tuple(full_idx)].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[tuple(full_idx)].multiply(v)
        if reduce == "amax":
            return a.at[tuple(full_idx)].max(v)
        if reduce == "amin":
            return a.at[tuple(full_idx)].min(v)
        raise ValueError(f"unknown reduce {reduce}")
    return apply("put_along_axis", f, arr, indices, values)


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, idx, upd):
        idx = idx.astype(jnp.int32).reshape(-1)
        if overwrite:
            return a.at[idx].set(upd.astype(a.dtype))
        # paddle semantics: zero the rows then accumulate
        zeroed = a.at[idx].set(jnp.zeros_like(upd, a.dtype))
        return zeroed.at[idx].add(upd.astype(a.dtype))
    return apply("scatter", f, x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._inplace_from(scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None):
    def f(a, idx, upd):
        ii = tuple(jnp.moveaxis(idx.astype(jnp.int32), -1, 0))
        return a.at[ii].add(upd.astype(a.dtype))
    return apply("scatter_nd_add", f, x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    s = _ints(shape)

    def f(idx, upd):
        out = jnp.zeros(s, upd.dtype)
        ii = tuple(jnp.moveaxis(idx.astype(jnp.int32), -1, 0))
        return out.at[ii].add(upd)
    return apply("scatter_nd", f, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply("index_select", lambda a, idx: jnp.take(a, idx.astype(jnp.int32), axis=axis), x, index)


def index_sample(x, index, name=None):
    return apply("index_sample",
                 lambda a, idx: jnp.take_along_axis(a, idx.astype(jnp.int64), axis=1), x, index)


def index_add(x, index, axis, value, name=None):
    def f(a, idx, v):
        a2 = jnp.moveaxis(a, axis, 0)
        v2 = jnp.moveaxis(v, axis, 0)
        out = a2.at[idx.astype(jnp.int32)].add(v2.astype(a.dtype))
        return jnp.moveaxis(out, 0, axis)
    return apply("index_add", f, x, index, value)


def index_put(x, indices, value, accumulate=False, name=None):
    def f(a, v, *idx):
        ii = tuple(i.astype(jnp.int64) if jnp.issubdtype(i.dtype, jnp.integer) else i for i in idx)
        if accumulate:
            return a.at[ii].add(v.astype(a.dtype))
        return a.at[ii].set(jnp.broadcast_to(v, a[ii].shape).astype(a.dtype))
    return apply("index_put", f, x, value, *indices)


def masked_select(x, mask, name=None):
    # dynamic shape: eager-only (not jittable) — reference has the same property on GPU
    data = _to_data(x)
    m = _to_data(mask)
    return Tensor(data[m])


def masked_fill(x, mask, value, name=None):
    v = value.item() if isinstance(value, Tensor) and value.size == 1 else value
    def f(a, m):
        return jnp.where(m, jnp.asarray(v, a.dtype), a)
    return apply("masked_fill", f, x, mask)


def masked_fill_(x, mask, value, name=None):
    return x._inplace_from(masked_fill(x, mask, value))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply("where", lambda c, a, b: jnp.where(c, a, b), condition, x, y)


def where_(condition, x, y, name=None):
    return x._inplace_from(where(condition, x, y))


def nonzero(x, as_tuple=False):
    data = np.asarray(_to_data(x))  # dynamic shape -> host
    nz = np.nonzero(data)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.reshape(-1, 1))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    p = _ints(pad)

    def f(a):
        nd = a.ndim
        if len(p) == 2 * nd:
            width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        else:
            # paddle NCHW convention: pad applies to last len(p)//2 spatial dims, reversed
            width = [(0, 0)] * nd
            np_ = len(p) // 2
            if data_format.endswith("HWC") or data_format in ("NLC", "NHWC", "NDHWC"):
                dims = list(range(1, 1 + np_))
            else:
                dims = list(range(nd - np_, nd))
            for i, d in enumerate(dims):
                width[d] = (p[2 * i], p[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, width, mode="constant", constant_values=value)
        return jnp.pad(a, width, mode=jmode)
    return apply("pad", f, x)


def cast(x, dtype):
    npd = _dt.to_np(dtype)
    return apply("cast", lambda a: a.astype(npd), x)


def slice(input, axes, starts, ends):
    ax = _ints(axes)
    st = _ints(starts)
    en = _ints(ends)

    def f(a):
        idx = [builtins.slice(None)] * a.ndim
        for i, axis in enumerate(ax):
            idx[axis] = builtins.slice(st[i], en[i])
        return a[tuple(idx)]
    return apply("slice", f, input)


def strided_slice(x, axes, starts, ends, strides, name=None):
    ax, st, en, sr = _ints(axes), _ints(starts), _ints(ends), _ints(strides)

    def f(a):
        idx = [builtins.slice(None)] * a.ndim
        for i, axis in enumerate(ax):
            idx[axis] = builtins.slice(st[i], en[i], sr[i])
        return a[tuple(idx)]
    return apply("strided_slice", f, x)


def unbind(input, axis=0):
    n = input.shape[axis]
    outs = apply("unbind", lambda a: tuple(jnp.moveaxis(a, axis, 0)[i] for i in range(n)), input)
    return list(outs)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None,
           dtype="int64", name=None):
    data = np.asarray(_to_data(x))  # dynamic shape -> host
    res = np.unique(data, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    data = np.asarray(_to_data(x))
    flat = data.reshape(-1) if axis is None else data
    if axis is None:
        keep = np.concatenate([[True], flat[1:] != flat[:-1]])
        out = flat[keep]
        outs = [Tensor(jnp.asarray(out))]
        if return_inverse:
            inv = np.cumsum(keep) - 1
            outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
        if return_counts:
            idx = np.nonzero(keep)[0]
            cnt = np.diff(np.append(idx, flat.size))
            outs.append(Tensor(jnp.asarray(cnt.astype(np.int64))))
        return outs[0] if len(outs) == 1 else tuple(outs)
    raise NotImplementedError("unique_consecutive with axis")


def as_complex(x, name=None):
    return apply("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def as_real(x, name=None):
    return apply("as_real", lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def as_strided(x, shape, stride, offset=0, name=None):
    def f(a):
        flat = a.reshape(-1)
        idx = offset + builtins.sum(
            (jnp.arange(s).reshape([-1 if i == d else 1 for i in range(len(shape))]) * st
             for d, (s, st) in enumerate(zip(shape, stride))))
        return flat[idx]
    return apply("as_strided", f, x)


def atleast_1d(*inputs, name=None):
    outs = [apply("atleast_1d", jnp.atleast_1d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply("atleast_2d", jnp.atleast_2d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply("atleast_3d", jnp.atleast_3d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def f(a):
        size = (index_num + nshards - 1) // nshards
        lo = shard_id * size
        inshard = (a >= lo) & (a < lo + size)
        return jnp.where(inshard, a - lo, ignore_value)
    return apply("shard_index", f, input)


def crop(x, shape=None, offsets=None, name=None):
    s = _ints(shape)
    off = _ints(offsets) if offsets is not None else (0,) * len(s)

    def f(a):
        idx = tuple(builtins.slice(off[i], off[i] + (s[i] if s[i] != -1 else a.shape[i] - off[i]))
                    for i in range(a.ndim))
        return a[idx]
    return apply("crop", f, x)


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size if isinstance(x, Tensor) else _to_data(x).size, jnp.int64))


def rank(input):
    return Tensor(jnp.asarray(_to_data(input).ndim, jnp.int32))


def shape(input):
    return Tensor(jnp.asarray(_to_data(input).shape, jnp.int32))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(_to_data(x).size == 0))


def is_complex(x):
    return jnp.issubdtype(_to_data(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(_to_data(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(_to_data(x).dtype, jnp.integer)


def rad2deg_(x):
    return x._inplace_from(apply("rad2deg", jnp.rad2deg, x))


# ---- breadth additions (reference python/paddle/tensor/manipulation.py) ----

def unflatten(x, axis, shape, name=None):
    """Split one axis into the given shape (ref manipulation.py unflatten)."""
    shape = [int(s) for s in shape]

    def f(a):
        ax = axis % a.ndim
        new = list(a.shape[:ax]) + shape + list(a.shape[ax + 1:])
        # resolve a single -1
        if -1 in shape:
            known = int(np.prod([s for s in shape if s != -1]))
            new[new.index(-1, ax)] = a.shape[ax] // known
        return a.reshape(new)
    return apply("unflatten", f, x)


def unfold(x, axis, size, step, name=None):
    """Sliding windows along axis (ref tensor.unfold): returns [..., n, size]."""
    def f(a):
        ax = axis % a.ndim
        n = (a.shape[ax] - size) // step + 1
        starts = jnp.arange(n) * step
        idx = starts[:, None] + jnp.arange(size)[None]       # [n, size]
        win = jnp.take(a, idx.reshape(-1), axis=ax)
        new = list(a.shape[:ax]) + [n, size] + list(a.shape[ax + 1:])
        win = win.reshape(new)
        # windows go to the END like the reference: [..., n, ...] -> [..., n, size]
        return jnp.moveaxis(win, ax + 1, -1)
    return apply("unfold", f, x)


def vsplit(x, num_or_indices, name=None):
    """Split along axis 0 (ref manipulation.py vsplit)."""
    return tensor_split(x, num_or_indices, axis=0)


def reverse(x, axis, name=None):
    """Deprecated alias of flip (ref legacy reverse op)."""
    return flip(x, axis)
