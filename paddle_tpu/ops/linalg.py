"""Linear algebra ops (reference: `python/paddle/tensor/linalg.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply, _to_data


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def f(a):
        if axis is None:
            if p in ("fro", 2):
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            if p == "nuc":
                return jnp.sum(jnp.linalg.svd(a, compute_uv=False))
            if p == np.inf:
                return jnp.max(jnp.abs(a))
            if p == -np.inf:
                return jnp.min(jnp.abs(a))
            return jnp.power(jnp.sum(jnp.power(jnp.abs(a), p)), 1.0 / p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if isinstance(ax, tuple) and p == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=keepdim))
        if p == np.inf:
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == -np.inf:
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        pp = 2 if p == "fro" else p
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a), pp), axis=ax, keepdims=keepdim), 1.0 / pp)
    return apply("norm", f, x)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p, axis, keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply("matrix_norm", lambda a: jnp.linalg.norm(a, ord=p, axis=tuple(axis), keepdims=keepdim), x)


def dist(x, y, p=2, name=None):
    return norm(x - y if isinstance(x, Tensor) else Tensor(_to_data(x)) - y, p)


def cond(x, p=None, name=None):
    return apply("cond", lambda a: jnp.linalg.cond(a, p=p), x)


def dot(x, y, name=None):
    return apply("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return apply("cholesky", f, x)


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply("cholesky_solve", f, x, y)


def inverse(x, name=None):
    return apply("inverse", jnp.linalg.inv, x)


inv = inverse


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), x)


def det(x, name=None):
    return apply("det", jnp.linalg.det, x)


def slogdet(x, name=None):
    def f(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])
    return apply("slogdet", f, x)


def matrix_power(x, n, name=None):
    return apply("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply("matrix_rank", lambda a: jnp.linalg.matrix_rank(a, tol), x)


def qr(x, mode="reduced", name=None):
    outs = apply("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x)
    return outs


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based
    outs = apply("lu", f, x)
    if get_infos:
        return outs[0], outs[1], Tensor(jnp.zeros((), jnp.int32))
    return outs


def svd(x, full_matrices=False, name=None):
    return apply("svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x)


def eig(x, name=None):
    return apply("eig", lambda a: tuple(jnp.linalg.eig(a)), x)


def eigh(x, UPLO="L", name=None):
    return apply("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x)


def eigvals(x, name=None):
    return apply("eigvals", jnp.linalg.eigvals, x)


def eigvalsh(x, UPLO="L", name=None):
    return apply("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x)


def solve(x, y, name=None):
    return apply("solve", jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply("triangular_solve", f, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rank_, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank_.astype(jnp.int32), sv
    return apply("lstsq", f, x, y)


def multi_dot(x, name=None):
    return apply("multi_dot", lambda *arrs: jnp.linalg.multi_dot(arrs), *x)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    from .math import matmul as _mm
    return _mm(x, y, transpose_x, transpose_y)


def bincount(x, weights=None, minlength=0, name=None):
    data = np.asarray(_to_data(x))
    w = np.asarray(_to_data(weights)) if weights is not None else None
    return Tensor(jnp.asarray(np.bincount(data, weights=w, minlength=minlength)))


def histogram(input, bins=100, min=0, max=0, name=None):
    data = np.asarray(_to_data(input))
    lo, hi = (min, max) if (min != 0 or max != 0) else (data.min(), data.max())
    hist, _ = np.histogram(data, bins=bins, range=(lo, hi))
    return Tensor(jnp.asarray(hist.astype(np.int64)))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    data = np.asarray(_to_data(x))
    w = np.asarray(_to_data(weights)) if weights is not None else None
    hist, edges = np.histogramdd(data, bins=bins, range=ranges, density=density, weights=w)
    return Tensor(jnp.asarray(hist)), [Tensor(jnp.asarray(e)) for e in edges]


def householder_product(x, tau, name=None):
    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else eye
        for i in range(n - 1, -1, -1):
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) < i, 0.0, v)
            v = v.at[..., i].set(1.0)
            ti = t[..., i][..., None, None]
            q = q - ti * v[..., :, None] * jnp.einsum("...m,...mn->...n", v, q)[..., None, :].swapaxes(-1, -2).swapaxes(-1, -2)
            q = q  # noqa
        return q[..., :, :]
    # simple reference implementation via loop (cold path)
    def f2(a, t):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(n - 1, -1, -1):
            v = a[:, i]
            v = jnp.where(jnp.arange(m) < i, 0.0, v).at[i].set(1.0)
            q = q - t[i] * jnp.outer(v, v @ q)
        return q
    return apply("householder_product", f2, x, tau)


def corrcoef(x, rowvar=True, name=None):
    return apply("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply("cov", lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), x)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    def f(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(diff), p), axis=-1), 1.0 / p)
    return apply("cdist", f, x, y)


def pdist(x, p=2.0, name=None):
    def f(a):
        n = a.shape[0]
        d = a[:, None, :] - a[None, :, :]
        full = jnp.power(jnp.sum(jnp.power(jnp.abs(d), p), axis=-1), 1.0 / p) if p != 2.0 \
            else jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-30)
        iu = jnp.triu_indices(n, k=1)
        return full[iu]
    return apply("pdist", f, x)


# ---- breadth additions (reference python/paddle/tensor/linalg.py) ----

def tensordot(x, y, axes=2, name=None):
    """ref linalg.py tensordot; axes int or (list, list)."""
    if isinstance(axes, Tensor):
        axes = axes.numpy().tolist()
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a.numpy().tolist()) if isinstance(a, Tensor)
                     else tuple(a) if isinstance(a, (list, tuple)) else (a,)
                     for a in axes)
        if len(axes) == 1:
            axes = (axes[0], axes[0])
    return apply("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack lu()'s packed LU + 1-based pivots into P, L, U (ref lu_unpack)."""
    def f(lu_, piv):
        m, n = lu_.shape[-2], lu_.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_.dtype)
        U = jnp.triu(lu_[..., :k, :])
        # pivots (1-based sequential row swaps) -> permutation, batched
        batch = piv.shape[:-1]
        perm = jnp.broadcast_to(jnp.arange(m), batch + (m,))
        for i in range(piv.shape[-1]):
            j = (piv[..., i] - 1).astype(jnp.int32)[..., None]     # [..., 1]
            pi = perm[..., i:i + 1]
            pj = jnp.take_along_axis(perm, j, axis=-1)
            perm = perm.at[..., i:i + 1].set(pj)
            perm = jnp.where(
                jnp.arange(m) == j, pi, perm)                      # scatter at j
        # P[..., i, c] = 1 iff perm[..., c] == i
        P = (perm[..., None, :] == jnp.arange(m)[:, None]).astype(lu_.dtype)
        return P, L, U
    P, L, U = apply("lu_unpack", f, x, y)
    return P, L, U


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Low-rank PCA (ref linalg.py pca_lowrank): returns (U, S, V)."""
    def f(a):
        m, n = a.shape[-2:]
        k = q if q is not None else min(6, m, n)
        c = a - jnp.mean(a, axis=-2, keepdims=True) if center else a
        u, s, vt = jnp.linalg.svd(c, full_matrices=False)
        return u[..., :k], s[..., :k], jnp.swapaxes(vt, -1, -2)[..., :k]
    return apply("pca_lowrank", f, x)
