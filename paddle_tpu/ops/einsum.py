"""einsum (reference: `python/paddle/tensor/einsum.py`)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import apply


def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return apply("einsum", lambda *arrs: jnp.einsum(equation, *arrs), *operands)
