"""Search/sort ops (reference: `python/paddle/tensor/search.py`)."""
from __future__ import annotations

import jax
import jax.lax as lax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core.tensor import Tensor, apply, _to_data


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        out = jnp.argmax(a if axis is not None else a.reshape(-1), axis=axis)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(_dt.to_np(dtype))
    return apply("argmax", f, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        out = jnp.argmin(a if axis is not None else a.reshape(-1), axis=axis)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(_dt.to_np(dtype))
    return apply("argmin", f, x)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        out = jnp.argsort(-a if descending else a, axis=axis, stable=stable or descending)
        return out.astype(jnp.int64)
    return apply("argsort", f, x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        out = jnp.sort(a, axis=axis)
        return jnp.flip(out, axis=axis) if descending else out
    return apply("sort", f, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)

    def f(a):
        ax = axis if axis is not None else a.ndim - 1
        moved = jnp.moveaxis(a, ax, -1)
        vals, idx = _topk_impl(moved, kk, largest)
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax).astype(jnp.int64)
    return apply("topk", f, x)


def _topk_impl(a, k, largest):
    if largest:
        return lax.top_k(a, k)
    vals, idx = lax.top_k(-a, k)
    return -vals, idx


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        srt = jnp.sort(a, axis=axis)
        ids = jnp.argsort(a, axis=axis)
        vals = jnp.take(srt, k - 1, axis=axis)
        inds = jnp.take(ids, k - 1, axis=axis).astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            inds = jnp.expand_dims(inds, axis)
        return vals, inds
    return apply("kthvalue", f, x)


def mode(x, axis=-1, keepdim=False, name=None):
    def f(a):
        srt = jnp.sort(a, axis=axis)
        n = a.shape[axis]
        moved = jnp.moveaxis(srt, axis, -1)
        runs = jnp.concatenate([jnp.ones(moved.shape[:-1] + (1,), bool),
                                moved[..., 1:] != moved[..., :-1]], axis=-1)
        run_id = jnp.cumsum(runs, axis=-1)
        counts = jnp.sum(run_id[..., :, None] == run_id[..., None, :], axis=-1)
        best = jnp.argmax(counts, axis=-1)
        vals = jnp.take_along_axis(moved, best[..., None], axis=-1)[..., 0]
        idx = jnp.argmax(jnp.moveaxis(a, axis, -1) == vals[..., None], axis=-1)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx.astype(jnp.int64)
    return apply("mode", f, x)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def f(seq, v):
        side = "right" if right else "left"
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, v, side=side)
        else:
            out = jnp.stack([jnp.searchsorted(seq[i], v[i], side=side)
                             for i in range(seq.shape[0])])
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply("searchsorted", f, sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    def f(v, seq):
        out = jnp.searchsorted(seq, v, side="right" if right else "left")
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply("bucketize", f, x, sorted_sequence)
