"""Logical/comparison ops (reference: `python/paddle/tensor/logic.py`)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply, _to_data


def _bin(name, jfn):
    def op(x, y, out=None, name=None):
        r = apply(nm, jfn, x, y)
        if out is not None:
            out._data = r._data
            return out
        return r
    nm = name
    op.__name__ = name
    return op


equal = _bin("equal", jnp.equal)
not_equal = _bin("not_equal", jnp.not_equal)
less_than = _bin("less_than", jnp.less)
less_equal = _bin("less_equal", jnp.less_equal)
greater_than = _bin("greater_than", jnp.greater)
greater_equal = _bin("greater_equal", jnp.greater_equal)
logical_and = _bin("logical_and", jnp.logical_and)
logical_or = _bin("logical_or", jnp.logical_or)
logical_xor = _bin("logical_xor", jnp.logical_xor)
bitwise_and = _bin("bitwise_and", jnp.bitwise_and)
bitwise_or = _bin("bitwise_or", jnp.bitwise_or)
bitwise_xor = _bin("bitwise_xor", jnp.bitwise_xor)


def logical_not(x, out=None, name=None):
    r = apply("logical_not", jnp.logical_not, x)
    if out is not None:
        out._data = r._data
        return out
    return r


def bitwise_not(x, out=None, name=None):
    return apply("bitwise_not", jnp.invert, x)


def equal_all(x, y, name=None):
    return apply("equal_all", lambda a, b: jnp.array_equal(a, b), x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("allclose", lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                                       equal_nan=equal_nan), x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("isclose", lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                                     equal_nan=equal_nan), x, y)
