"""Tensor creation ops (reference: `python/paddle/tensor/creation.py`)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core.tensor import Tensor, apply, to_tensor, _to_data


def _npd(dtype, default=None):
    d = _dt.to_np(dtype) if dtype is not None else None
    if d is None and default is not None:
        d = _dt.to_np(default)
    return d


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _npd(dtype, _dt._default_dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _npd(dtype, _dt._default_dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fv = fill_value.item() if isinstance(fill_value, Tensor) else fill_value
    if dtype is None:
        dtype = _dt._default_dtype if isinstance(fv, float) else None
    return Tensor(jnp.full(_shape(shape), fv, _npd(dtype)))


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(_to_data(x), dtype=_npd(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(_to_data(x), dtype=_npd(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(_to_data(x), fill_value, dtype=_npd(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = _dt._default_dtype if any(isinstance(v, float) for v in (start, end, step)) else _dt.int64
    return Tensor(jnp.arange(start, end, step, _npd(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.linspace(val(start), val(stop), int(val(num)),
                               dtype=_npd(dtype, _dt._default_dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base,
                               dtype=_npd(dtype, _dt._default_dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), None if num_columns is None else int(num_columns),
                          dtype=_npd(dtype, _dt._default_dtype)))


def meshgrid(*args, **kwargs):
    datas = [_to_data(a) for a in args]
    outs = jnp.meshgrid(*datas, indexing="ij")
    return [Tensor(o) for o in outs]


def diag(x, offset=0, padding_value=0, name=None):
    def f(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.eye(*out.shape, k=offset, dtype=bool)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(a, offset=offset)
    return apply("diag", f, x)


def diagflat(x, offset=0, name=None):
    return apply("diagflat", lambda a: jnp.diagflat(a, k=offset), x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def f(a):
        n = a.shape[-1] + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        if offset >= 0:
            out = out.at[..., idx, idx + offset].set(a)
        else:
            out = out.at[..., idx - offset, idx].set(a)
        if (dim1, dim2) != (-2, -1):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out
    return apply("diag_embed", f, x)


def tril(x, diagonal=0, name=None):
    return apply("tril", lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply("triu", lambda a: jnp.triu(a, k=diagonal), x)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.stack([jnp.asarray(r), jnp.asarray(c)]).astype(_npd(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.stack([jnp.asarray(r), jnp.asarray(c)]).astype(_npd(dtype)))


def assign(x, output=None):
    data = _to_data(x)
    if output is None:
        return Tensor(data)
    output._data = data.astype(output._data.dtype)
    return output


def clone(x, name=None):
    return x.clone() if isinstance(x, Tensor) else Tensor(_to_data(x))


def complex(real, imag, name=None):
    return apply("complex", lambda r, i: r + 1j * i, real, imag)


def polar(abs_t, angle, name=None):
    return apply("polar", lambda a, th: a * jnp.exp(1j * th), abs_t, angle)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..core.tensor import Parameter
    p = Parameter(jnp.zeros(_shape(shape), _npd(dtype)), name=name)
    if default_initializer is not None:
        default_initializer(p)
    return p


# ---- breadth additions (reference python/paddle/tensor/creation.py) ----

def vander(x, n=None, increasing=False, name=None):
    """Vandermonde matrix (ref creation.py vander)."""
    def f(a):
        cols = a.shape[0] if n is None else int(n)
        p = jnp.arange(cols)
        if not increasing:
            p = p[::-1]
        return a[:, None].astype(jnp.promote_types(a.dtype, jnp.float32)) ** p[None]
    return apply("vander", f, x)


def create_tensor(dtype, name=None, persistable=False):
    """ref creation.py create_tensor: an empty typed tensor handle."""
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    from ..core import dtype as _dtm
    return Tensor(_jnp.zeros((0,), _dtm.to_np(dtype)))
