"""Trailing-underscore in-place op variants.

Reference parity: `python/paddle/tensor/math.py` etc. register `<op>_` dygraph-only
in-place APIs (inplace_apis_in_dygraph_only).  Under the eager tape, in-place means
rebinding the tensor handle to the out-of-place result's tape node
(`Tensor._inplace_from`), which preserves correct gradients — the same view
semantics the reference's inplace version counter guards.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import linalg, logic, manipulation, math


def _inplace(fn, name):
    def op_(x, *args, **kwargs):
        return x._inplace_from(fn(x, *args, **kwargs))
    op_.__name__ = name
    op_.__qualname__ = name
    op_.__doc__ = f"In-place variant of `{fn.__module__.split('.')[-1]}.{fn.__name__}`."
    return op_


_SPECS = {
    math: [
        "abs", "acos", "asin", "atan", "ceil", "clip", "cos", "cosh", "digamma",
        "erf", "erfinv", "exp", "expm1", "floor", "frac", "i0", "lerp", "lgamma",
        "log", "log10", "log1p", "log2", "logit", "multiply", "neg", "polygamma",
        "pow", "reciprocal", "remainder", "round", "rsqrt", "sigmoid", "sin",
        "sinh", "sqrt", "square", "subtract", "tan", "tanh", "trunc", "addmm",
        "divide", "floor_divide", "mod", "nan_to_num",
    ],
    logic: [
        "greater_equal", "greater_than", "less_equal", "less_than", "not_equal",
        "equal", "logical_and", "logical_not", "logical_or", "logical_xor",
        "bitwise_and", "bitwise_not", "bitwise_or", "bitwise_xor",
    ],
    manipulation: [
        "flatten", "index_put", "put_along_axis", "cast",
    ],
}

__all__ = []
for _mod, _names in _SPECS.items():
    for _n in _names:
        _fn = getattr(_mod, _n, None)
        if _fn is None:
            continue
        _name = _n + "_"
        globals()[_name] = _inplace(_fn, _name)
        __all__.append(_name)


def tril_(x, diagonal=0, name=None):
    from .creation import tril
    return x._inplace_from(tril(x, diagonal))


def triu_(x, diagonal=0, name=None):
    from .creation import triu
    return x._inplace_from(triu(x, diagonal))


def renorm_(x, p, axis, max_norm, name=None):
    return x._inplace_from(math.renorm(x, p, axis, max_norm))


__all__ += ["tril_", "triu_", "renorm_"]


def add_(x, y, name=None):
    return x.add_(y)


def scale_(x, scale=1.0, bias=0.0, name=None):
    return x.scale_(scale, bias)


__all__ += ["add_", "scale_"]
