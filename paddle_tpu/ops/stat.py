"""Statistics ops (reference: `python/paddle/tensor/stat.py`)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply, _to_data


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def mean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("mean", lambda a: jnp.mean(a, axis=ax, keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("var", lambda a: jnp.var(a, axis=ax, ddof=1 if unbiased else 0,
                                          keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axis(axis)
    return apply("std", lambda a: jnp.std(a, axis=ax, ddof=1 if unbiased else 0,
                                          keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _axis(axis)

    def f(a):
        if mode == "avg":
            return jnp.median(a, axis=ax, keepdims=keepdim)
        # min mode: lower of the two middles
        arr = a.reshape(-1) if ax is None else a
        use_ax = 0 if ax is None else ax
        srt = jnp.sort(arr, axis=use_ax)
        n = srt.shape[use_ax]
        out = jnp.take(srt, (n - 1) // 2, axis=use_ax)
        if keepdim and ax is not None:
            out = jnp.expand_dims(out, ax)
        return out
    return apply("median", f, x)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _axis(axis)
    return apply("nanmedian", lambda a: jnp.nanmedian(a, axis=ax, keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = _axis(axis)
    qq = _to_data(q) if isinstance(q, Tensor) else jnp.asarray(q)
    return apply("quantile", lambda a: jnp.quantile(a.astype(jnp.float32), qq, axis=ax,
                                                    keepdims=keepdim, method=interpolation), x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    ax = _axis(axis)
    qq = _to_data(q) if isinstance(q, Tensor) else jnp.asarray(q)
    return apply("nanquantile", lambda a: jnp.nanquantile(a.astype(jnp.float32), qq, axis=ax,
                                                          keepdims=keepdim, method=interpolation), x)
