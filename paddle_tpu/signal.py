"""paddle.signal — frame / overlap_add / stft / istft.

Reference parity: `python/paddle/signal.py` (frame/overlap_add ops in
`phi/kernels/frame_kernel.*`, stft composed from frame+matmul FFT).  TPU-native:
framing is a static-shape gather (XLA-friendly), transforms ride jnp.fft.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .core.tensor import Tensor, apply


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into overlapping frames (ref signal.py frame).

    x [..., seq_len] (axis=-1) -> [..., frame_length, num_frames], or
    x [seq_len, ...] (axis=0) -> [num_frames, frame_length, ...].
    """
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")

    def f(a):
        n = a.shape[axis]
        if frame_length > n:
            raise ValueError(f"frame_length {frame_length} > input size {n}")
        nf = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(nf) * hop_length
        if axis in (-1, a.ndim - 1):
            idx = starts[None, :] + jnp.arange(frame_length)[:, None]  # [fl, nf]
            return jnp.take(a, idx.reshape(-1), axis=-1).reshape(
                a.shape[:-1] + (frame_length, nf))
        idx = starts[:, None] + jnp.arange(frame_length)[None]        # [nf, fl]
        return jnp.take(a, idx.reshape(-1), axis=0).reshape(
            (nf, frame_length) + a.shape[1:])
    return apply("frame", f, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: sum overlapping frames (ref signal.py overlap_add)."""
    def f(a):
        if axis in (-1, a.ndim - 1):
            fl, nf = a.shape[-2], a.shape[-1]
            out_len = (nf - 1) * hop_length + fl
            lead = a.shape[:-2]
            buf = jnp.zeros(lead + (out_len,), a.dtype)
            pos = (jnp.arange(nf)[None, :] * hop_length +
                   jnp.arange(fl)[:, None]).reshape(-1)                # [fl*nf]
            vals = a.reshape(lead + (fl * nf,))
            return buf.at[..., pos].add(vals)
        nf, fl = a.shape[0], a.shape[1]
        out_len = (nf - 1) * hop_length + fl
        buf = jnp.zeros((out_len,) + a.shape[2:], a.dtype)
        pos = (jnp.arange(nf)[:, None] * hop_length +
               jnp.arange(fl)[None]).reshape(-1)
        vals = a.reshape((nf * fl,) + a.shape[2:])
        return buf.at[pos].add(vals)
    return apply("overlap_add", f, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (ref signal.py stft).

    x [B, T] or [T] -> complex [B, n_fft//2+1 (or n_fft), num_frames].
    """
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    wdata = None if window is None else (
        window._data if isinstance(window, Tensor) else jnp.asarray(window))

    def f(a):
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None]
        w = jnp.ones((wl,), a.dtype) if wdata is None else wdata
        # center-pad window to n_fft like the reference
        if wl < n_fft:
            lp = (n_fft - wl) // 2
            w = jnp.pad(w, (lp, n_fft - wl - lp))
        if center:
            pad = n_fft // 2
            a = jnp.pad(a, ((0, 0), (pad, pad)), mode=pad_mode)
        n = a.shape[-1]
        nf = 1 + (n - n_fft) // hop
        idx = (jnp.arange(nf)[None, :] * hop +
               jnp.arange(n_fft)[:, None]).reshape(-1)
        frames = jnp.take(a, idx, axis=-1).reshape(a.shape[0], n_fft, nf)
        frames = frames * w[None, :, None]
        spec = (jnp.fft.rfft(frames, axis=1) if onesided
                else jnp.fft.fft(frames, axis=1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return spec[0] if squeeze else spec
    return apply("stft", f, x)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with window-envelope normalization (ref signal.py istft)."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    wdata = None if window is None else (
        window._data if isinstance(window, Tensor) else jnp.asarray(window))

    def f(sp):
        squeeze = sp.ndim == 2
        if squeeze:
            sp = sp[None]
        w = jnp.ones((wl,), jnp.float32) if wdata is None else wdata
        if wl < n_fft:
            lp = (n_fft - wl) // 2
            w = jnp.pad(w, (lp, n_fft - wl - lp))
        if normalized:
            sp = sp * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = (jnp.fft.irfft(sp, n=n_fft, axis=1) if onesided
                  else jnp.fft.ifft(sp, axis=1).real)       # [B, n_fft, nf]
        frames = frames * w[None, :, None]
        nf = frames.shape[-1]
        out_len = (nf - 1) * hop + n_fft
        pos = (jnp.arange(nf)[None, :] * hop +
               jnp.arange(n_fft)[:, None]).reshape(-1)
        buf = jnp.zeros((frames.shape[0], out_len), frames.dtype)
        buf = buf.at[:, pos].add(frames.reshape(frames.shape[0], -1))
        env = jnp.zeros((out_len,), frames.dtype)
        env = env.at[pos].add(jnp.broadcast_to((w * w)[:, None],
                                               (n_fft, nf)).reshape(-1))
        buf = buf / jnp.maximum(env, 1e-11)[None]
        if center:
            pad = n_fft // 2
            buf = buf[:, pad:out_len - pad]
        if length is not None:
            buf = buf[:, :length]
        return buf[0] if squeeze else buf
    return apply("istft", f, x)


__all__ = ["frame", "overlap_add", "stft", "istft"]
