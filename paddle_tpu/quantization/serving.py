"""Quantized serving: weight-only int8 params + int8 KV page pool helpers.

Reference parity: the reference ships a full QAT/PTQ layer
(`quantization/imperative/qat.py`, PTQ observers/quanters) whose deployment
form is int8 weights + scales dequantized into the matmul, and an int8
predictor path through `fluid/inference`.  This module is the SERVING face of
that layer for the paged engine (`inference.engine.LLMEngine`): the eager
`QAT`/`PTQ`/`Int8Linear` classes in `quantization/__init__.py` quantize
nn.Layer trees; here we quantize the functional `models.gpt` serving param
pytree and size the int8 KV page pool.

Two independent knobs (`LLMEngine(weight_dtype=, kv_dtype=)`):

- **Weight-only int8** (`quantize_serving_params`): symmetric per-channel PTQ
  of every serving matmul weight — `blocks.{qkv,proj,fc1,fc2,fcg}_w`, the
  tied embedding/head `wte` and an untied `lm_head`.  Channel = the
  NON-contracting dim of the serving matmul, so the scale vector shards with
  the weight's sharded dim under tensor parallelism (qkv/fc1/fcg: output
  columns, mp-sharded; proj/fc2: output columns, replicated like the
  row-parallel output; wte: vocab rows, replicated).  A quantized leaf `w`
  is stored as the PAIR `w_q` (int8) + `w_scale` (float32, broadcastable) —
  `models.gpt._w` dequantizes per BLOCK inside the layer scan, so the fp
  copy of a weight only ever exists one layer at a time (at-rest HBM drops
  ~4x vs fp32, ~2x vs bf16; the transient is one block's weights).
- **int8 KV pages** (`init_paged_cache(kv_dtype="int8")`, in `models.gpt`):
  the pool stores int8 k/v plus per-token-per-head float32 scales
  (`k_scale`/`v_scale`, `[L, P, page, KVH]` — the finest granularity of the
  ISSUE's "per-page (or per-page-per-head) scale" family).  Per-token scales
  are the one choice that keeps token-granular writes (decode, chunked
  prefill, verify rollback) exact and write-order independent: a per-page
  scale would need a lossy re-quantization of already-written tokens
  whenever a later token's absmax exceeded it.  Writes quantize in-program
  (`models.gpt._quantize_kv`); the paged-attention kernels and XLA oracles
  dequantize per page on read (`kv_scales=` lane).

Both knobs default OFF and the fp path is byte-identical to a
quantization-free engine (asserted by tests/test_quantized_serving.py).

Everything here is host-side numpy — no jit sites, no new compiled programs
(the dequant lives inside the existing serving executables; see
`tools/check_program_count.py`).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

INT8_QMAX = 127.0
# scale floor: keeps a zero channel/token from dividing by zero; quantized
# values of an all-zero vector are exactly 0 either way
SCALE_EPS = 1e-30

# serving matmul weights inside the stacked blocks tree and the channel
# (non-contracting) axis of each — all are [L, in, out] with channel = last
BLOCK_WEIGHT_KEYS = ("qkv_w", "proj_w", "fc1_w", "fc2_w", "fcg_w")

KV_SCALE_DTYPE = np.float32


def quantize_weight(w, channel_axis):
    """Symmetric per-channel int8 PTQ of one weight (host numpy).

    `channel_axis` (an int or tuple) names the dims whose entries each get
    their own scale — the non-contracting dim of the serving matmul, plus
    the leading layer dim for stacked block weights.  Returns (q int8,
    scale float32) with `scale` keeping `w`'s rank (size-1 on every reduced
    dim) so `q * scale` broadcasts back to the weight's shape."""
    w = np.asarray(w, np.float32)
    keep = (channel_axis,) if isinstance(channel_axis, int) else \
        tuple(channel_axis)
    axes = tuple(i for i in range(w.ndim) if i not in keep)
    absmax = np.max(np.abs(w), axis=axes, keepdims=True)
    scale = (np.maximum(absmax, SCALE_EPS) / INT8_QMAX).astype(np.float32)
    q = np.clip(np.round(w / scale), -INT8_QMAX, INT8_QMAX).astype(np.int8)
    return q, scale


def dequantize_weight(q, scale, dtype=np.float32):
    """Inverse of `quantize_weight` (the same math `models.gpt._w` traces)."""
    return (np.asarray(q, np.float32) * np.asarray(scale, np.float32)) \
        .astype(dtype)


def _block_scale(q, scale):
    """Normalize a stacked-block scale to [L, 1, out]: per-layer, per-output-
    channel (the keepdims reduction above already yields this shape)."""
    assert scale.shape == (q.shape[0], 1, q.shape[2]), scale.shape
    return scale


def quantize_serving_params(params: Dict[str, Any], config
                            ) -> Dict[str, Any]:
    """Weight-only int8 PTQ of a `models.gpt` serving param pytree.

    Every quantized weight `name` is REPLACED by the pair `name_q` (int8) +
    `name_scale` (float32); biases, norms and anything this function does
    not recognize (MoE expert banks, BERT-only leaves) pass through
    unquantized.  Stacked block weights `[L, in, out]` quantize per
    (layer, output-channel) — scale `[L, 1, out]`, which the layer scan
    slices to `[1, out]` per block so dequant broadcasts over the
    contraction dim.  `wte [V, D]` quantizes per vocab ROW (scale `[V, 1]`):
    the row is both the embedding-gather unit and the head matmul's
    non-contracting dim, so one scale serves both uses.  An untied
    `lm_head [D, V]` quantizes per vocab COLUMN (scale `[1, V]`).

    Host-side numpy in and out — the engine quantizes ONCE at init, before
    mp placement (`serving_param_specs` knows the `_q`/`_scale` layout)."""
    del config      # the key structure alone determines the treatment
    out: Dict[str, Any] = {}
    for name, leaf in params.items():
        if name == "blocks":
            blocks: Dict[str, Any] = {}
            for k, w in leaf.items():
                if k in BLOCK_WEIGHT_KEYS:
                    # per (layer, output channel): axes (0, 2) of [L, in, out]
                    q, s = quantize_weight(np.asarray(w), channel_axis=(0, 2))
                    blocks[k + "_q"] = q
                    blocks[k + "_scale"] = _block_scale(q, s)
                else:
                    blocks[k] = w
            out["blocks"] = blocks
        elif name == "wte":
            q, s = quantize_weight(np.asarray(leaf), channel_axis=0)
            out["wte_q"], out["wte_scale"] = q, s
        elif name == "lm_head":
            q, s = quantize_weight(np.asarray(leaf), channel_axis=1)
            out["lm_head_q"], out["lm_head_scale"] = q, s
        else:
            out[name] = leaf
    return out


def normalize_quant_dtype(value: Optional[str], knob: str) -> Optional[str]:
    """Engine/bench knob normalization: None / fp names mean OFF, "int8" is
    the one quantized form; anything else raises."""
    if value in (None, "fp", "fp32", "f32", "bf16", "bfloat16", "float32"):
        return None
    if value == "int8":
        return "int8"
    raise ValueError(f"{knob} must be None/'bf16' (off) or 'int8', "
                     f"got {value!r}")


def kv_page_bytes(config, page_size: int,
                  kv_dtype: Optional[str] = None) -> int:
    """At-rest bytes ONE page pool page occupies across all layers (k + v,
    plus the per-token scale lanes when quantized) — the formula the engine's
    `swap_pool_bytes`, the bench's equal-byte pool sizing and the
    `tpu_cost` accounts all agree on."""
    L, KVH, hd = config.num_layers, config.kv_heads, config.head_dim
    if normalize_quant_dtype(kv_dtype, "kv_dtype") == "int8":
        per_tok = hd * 1 + np.dtype(KV_SCALE_DTYPE).itemsize
    else:
        per_tok = hd * np.dtype(config.dtype).itemsize
    return 2 * L * page_size * KVH * per_tok


__all__ = [
    "BLOCK_WEIGHT_KEYS", "INT8_QMAX", "KV_SCALE_DTYPE",
    "quantize_weight", "dequantize_weight", "quantize_serving_params",
    "normalize_quant_dtype", "kv_page_bytes",
]
