"""paddle.quantization — QAT (fake-quant training) and PTQ (observer calibration).

Reference parity: `python/paddle/quantization/` (QuantConfig, QAT, PTQ,
quanters/observers) and `quantization/imperative/qat.py`
(ImperativeQuantAware).

TPU-native design: fake-quantization is a straight-through-estimator op pair
(quantize -> dequantize with stop_gradient on the rounding), which XLA fuses
into the surrounding matmul; `convert()` produces layers holding int8 weights +
scales whose forward dequantizes into the bf16 MXU matmul (weight-only int8 —
the TPU-serving quantization form).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ..nn.layer.layers import Layer


def fake_quant(x, scale, bits=8):
    """Symmetric fake-quant with straight-through estimator (ref
    FakeQuanterWithAbsMax)."""
    qmax = 2.0 ** (bits - 1) - 1

    def f(a, s):
        s = jnp.maximum(s, 1e-8)
        q = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax) * s / qmax
        # STE: forward quantized, gradient of identity
        return a + jax.lax.stop_gradient(q - a)
    return apply("fake_quant", f, x, scale)


class AbsmaxObserver:
    """ref observers.AbsmaxObserver: tracks max |x| for the scale."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def observe(self, x):
        data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        self._absmax = max(self._absmax, float(jnp.max(jnp.abs(data))))

    def scale(self):
        return self._absmax if self._absmax > 0 else 1.0


class FakeQuanterWithAbsMaxObserver(Layer):
    """QAT quanter: running-absmax scale + STE fake quant (ref
    quanters/abs_max.py)."""

    def __init__(self, moving_rate=0.9, quant_bits=8, name=None, **kwargs):
        super().__init__()
        self.quant_bits = quant_bits
        self.moving_rate = moving_rate
        self._scale = 1.0
        self._initialized = False

    def forward(self, x):
        data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        cur = float(jnp.max(jnp.abs(data)))
        if self.training:
            if not self._initialized:
                self._scale = max(cur, 1e-8)
                self._initialized = True
            else:
                r = self.moving_rate
                self._scale = r * self._scale + (1 - r) * cur
        return fake_quant(x, Tensor(jnp.asarray(self._scale, jnp.float32)),
                          self.quant_bits)


class QuantConfig:
    """ref config.QuantConfig: which layers get which quanters."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._types = []

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        self._types.append((tuple(layer_types), activation, weight))

    def _quanters_for(self, layer):
        for types, act, w in self._types:
            if isinstance(layer, types):
                return act, w
        return self.activation, self.weight


class QuantedLinear(Layer):
    """Linear wrapped with weight/activation fake-quant (QAT sim)."""

    def __init__(self, linear, act_quanter, wt_quanter):
        super().__init__()
        self._inner = linear
        self.act_quanter = act_quanter() if callable(act_quanter) else act_quanter
        self.wt_quanter = wt_quanter() if callable(wt_quanter) else wt_quanter

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        if self.act_quanter is not None:
            x = self.act_quanter(x)
        w = self._inner.weight
        if self.wt_quanter is not None:
            w = self.wt_quanter(w)
        return F.linear(x, w, self._inner.bias)


class Int8Linear(Layer):
    """Deployment form: int8 weights + f32 scale, dequantized into the MXU
    matmul (weight-only int8)."""

    def __init__(self, linear, bits=8):
        super().__init__()
        qmax = 2.0 ** (bits - 1) - 1
        w = linear.weight._data
        scale = jnp.maximum(jnp.max(jnp.abs(w)), 1e-8)
        self.qweight = jnp.clip(jnp.round(w / scale * qmax), -qmax,
                                qmax).astype(jnp.int8)
        self.scale = float(scale / qmax)
        self.bias = linear.bias

    def forward(self, x):
        qw, s = self.qweight, self.scale

        def f(a, *b):
            out = jnp.matmul(a, qw.astype(a.dtype)) * s
            if b:
                out = out + b[0]
            return out
        args = (x,) + ((self.bias,) if self.bias is not None else ())
        return apply("int8_linear", f, *args)


def _swap_linears(model, make):
    from ..nn.layer.common import Linear
    # Layer tree walk via _sub_layers
    for name, sub in list(getattr(model, "_sub_layers", {}).items()):
        if isinstance(sub, Linear):
            model._sub_layers[name] = make(sub)
        else:
            _swap_linears(sub, make)
    return model


class QAT:
    """Quantization-aware training driver (ref qat.py QAT)."""

    def __init__(self, config: QuantConfig = None):
        self._config = config or QuantConfig(
            activation=FakeQuanterWithAbsMaxObserver,
            weight=FakeQuanterWithAbsMaxObserver)

    def quantize(self, model, inplace=True):
        cfg = self._config

        def make(lin):
            act, w = cfg._quanters_for(lin)
            return QuantedLinear(lin, act, w)
        return _swap_linears(model, make)

    def convert(self, model, inplace=True):
        def unmake(q):
            return Int8Linear(q._inner) if isinstance(q, QuantedLinear) else q

        for name, sub in list(getattr(model, "_sub_layers", {}).items()):
            if isinstance(sub, QuantedLinear):
                model._sub_layers[name] = Int8Linear(sub._inner)
            else:
                self.convert(sub)
        return model


class PTQ:
    """Post-training quantization: observe activations on calibration data,
    then convert to int8-weight layers (ref ptq.py PTQ)."""

    def __init__(self, config: QuantConfig = None):
        self._config = config
        self._observers = []

    def quantize(self, model, inplace=True):
        ptq = self

        class _Observed(Layer):
            def __init__(self, lin):
                super().__init__()
                self._inner = lin
                self.observer = AbsmaxObserver()
                ptq._observers.append(self.observer)

            def forward(self, x):
                self.observer.observe(x)
                return self._inner(x)

        return _swap_linears(model, _Observed)

    def convert(self, model, inplace=True):
        for name, sub in list(getattr(model, "_sub_layers", {}).items()):
            if hasattr(sub, "observer") and hasattr(sub, "_inner"):
                model._sub_layers[name] = Int8Linear(sub._inner)
            else:
                self.convert(sub)
        return model


class ImperativeQuantAware:
    """ref quantization/imperative/qat.py ImperativeQuantAware."""

    def __init__(self, quantizable_layer_type=None, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9, **kwargs):
        self._qat = QAT(QuantConfig(
            activation=lambda: FakeQuanterWithAbsMaxObserver(
                moving_rate, activation_bits),
            weight=lambda: FakeQuanterWithAbsMaxObserver(
                moving_rate, weight_bits)))

    def quantize(self, model):
        return self._qat.quantize(model)

    def save_quantized_model(self, model, path, input_spec=None, **config):
        from ..jit import save
        converted = self._qat.convert(model)
        save(converted, path, input_spec=input_spec)


from . import serving  # noqa: E402  (int8 serving params + KV page pool)
from .serving import (  # noqa: E402
    dequantize_weight, kv_page_bytes, quantize_serving_params,
    quantize_weight)

__all__ = ["QuantConfig", "QAT", "PTQ", "ImperativeQuantAware", "fake_quant",
           "AbsmaxObserver", "FakeQuanterWithAbsMaxObserver", "QuantedLinear",
           "Int8Linear", "serving", "quantize_serving_params",
           "quantize_weight", "dequantize_weight", "kv_page_bytes"]
