"""AMP autocast (reference: `python/paddle/amp/auto_cast.py:687`, `decorate` :755).

Hooks into `core.tensor.apply` — the same interposition point as the reference's
AMP_LOGIC stage in generated ad_funcs.  bf16-first: O1 casts white-list op inputs to
bf16 (TPU-native), black-list to fp32; O2 casts parameters once (decorate) and keeps
master weights in the optimizer.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..core import dtype as _dt
from ..core import tensor as _tensor_mod
from ..core.tensor import Tensor
from . import amp_lists


class _AmpState:
    def __init__(self, enabled, dtype, level, custom_white_list, custom_black_list):
        self.enabled = enabled
        self.dtype = _dt.to_np(dtype)
        self.level = level
        self.white = amp_lists.white_list() | set(custom_white_list or ())
        self.black = (amp_lists.black_list() | set(custom_black_list or ())) - set(custom_white_list or ())

    def cast_inputs(self, op_name, inputs):
        if self.level == "O2":
            # O2: everything except black list runs in low precision
            target = jnp.float32 if op_name in self.black else self.dtype
        elif op_name in self.white:
            target = self.dtype
        elif op_name in self.black:
            target = jnp.float32
        else:
            return inputs  # gray: leave as-is
        out = []
        for x in inputs:
            if isinstance(x, Tensor) and jnp.issubdtype(x._data.dtype, jnp.floating) \
                    and x._data.dtype != jnp.float64 and x._data.dtype != target:
                out.append(x.astype(target))
            else:
                out.append(x)
        return out


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1",
              dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast: bf16 by default on TPU (fp16 accepted and honoured)."""
    prev = _tensor_mod._amp_state
    state = _AmpState(enable, dtype, level, custom_white_list, custom_black_list) \
        if enable else None
    _tensor_mod._set_amp_state(state)
    try:
        yield
    finally:
        _tensor_mod._set_amp_state(prev)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2 decoration: cast model params to low precision; optimizers keep fp32
    accumulators (they already do — see optimizer/*: all state is fp32 = master
    weights)."""
    from ..nn.layer.layers import Layer
    from ..nn.layer.norm import _BatchNormBase, LayerNorm

    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models)
    if level == "O2":
        npd = _dt.to_np(dtype)
        excluded = tuple(excluded_layers) if excluded_layers else (_BatchNormBase, LayerNorm)
        for m in model_list:
            for lyr in m.sublayers(include_self=True):
                if isinstance(lyr, excluded):
                    continue
                for p in lyr._parameters.values():
                    if p is not None and jnp.issubdtype(p._data.dtype, jnp.floating):
                        p._data = p._data.astype(npd)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers
