from .auto_cast import amp_guard, auto_cast, decorate  # noqa
from .grad_scaler import AmpScaler, GradScaler  # noqa
from . import debugging  # noqa
