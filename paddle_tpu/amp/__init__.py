from .auto_cast import amp_guard, auto_cast, decorate  # noqa
from .grad_scaler import AmpScaler, GradScaler  # noqa
from . import debugging  # noqa


def is_bfloat16_supported(device=None):
    """TPU MXU is bf16-native."""
    return True


def is_float16_supported(device=None):
    import jax
    return jax.default_backend() != "cpu"
