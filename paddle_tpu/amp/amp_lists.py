"""AMP op lists (reference: `python/paddle/amp/amp_lists.py:98`).

White list: matmul-class ops that benefit from bf16 on the MXU.
Black list: numerically sensitive ops kept in fp32.
"""

WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum", "addmm",
    "fused_dot_product_attention", "flash_attn",
}

BLACK_LIST = {
    "exp", "square", "log", "log2", "log10", "log1p", "mean", "sum", "cos_sim",
    "softmax", "log_softmax", "softmax_with_cross_entropy", "cross_entropy",
    "sigmoid_cross_entropy_with_logits", "bce_with_logits", "c_softmax_with_cross_entropy",
    "layer_norm", "batch_norm", "group_norm", "instance_norm", "rms_norm",
    "reduce_sum", "linear_interp", "nll_loss", "mse_loss", "l1_loss", "kl_div",
    "logsumexp", "erfinv", "pow", "norm", "var", "std", "renorm",
}

# everything else is "gray": runs in whatever dtype its inputs arrive in


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)
