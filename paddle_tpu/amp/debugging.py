"""AMP debugging (reference: `python/paddle/amp/debugging.py` — tensor checker,
low-precision op audit)."""
from __future__ import annotations

import contextlib
from collections import Counter

from ..core import flags as _flags

_op_counter = Counter()
_checking = False


def enable_operator_stats_collection():
    _op_counter.clear()
    _flags.set_flags({"FLAGS_low_precision_op_list": 1})


def disable_operator_stats_collection():
    _flags.set_flags({"FLAGS_low_precision_op_list": 0})
    print("<------------------- op list -------------------->")
    for op, cnt in _op_counter.most_common():
        print(f"  {op}: {cnt}")


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def _record_op(name, dtype):
    if _flags.flag("low_precision_op_list"):
        _op_counter[f"{name}:{dtype}"] += 1


def enable_tensor_checker(checker_config=None):
    _flags.set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    _flags.set_flags({"FLAGS_check_nan_inf": False})


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=None, output_dir=None, **kw):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
