"""GradScaler (reference: `python/paddle/amp/grad_scaler.py:576`).

Dynamic loss scaling with found_inf tracking.  On TPU the default AMP dtype is bf16
(same exponent range as fp32), so scaling is usually a no-op — `enable` follows the
reference API and the machinery is fully implemented for fp16 workloads.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.tensor import Tensor


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=65536.0, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def _opt_state(self, optimizer):
        # per-optimizer state (reference OptimizerState): a scaler may serve
        # several optimizers (e.g. GAN G/D) with independent unscale/inf status.
        # WeakKeyDictionary so dead optimizers don't pin state (and a reused id()
        # can't alias a new optimizer)
        import weakref
        states = getattr(self, "_opt_states", None)
        if states is None:
            states = self._opt_states = weakref.WeakKeyDictionary()
        return states.setdefault(optimizer, {"unscaled": False, "found_inf": False})

    def unscale_(self, optimizer):
        if not self._enable:
            return
        state = self._opt_state(optimizer)
        if state["unscaled"]:
            # unscaling twice before step() would silently shrink the update
            raise RuntimeError(
                "unscale_() has already been called on this optimizer since the "
                "last step()")
        inv = 1.0 / self._scale
        # accumulate the inf/nan flag ON DEVICE across the parameter loop and
        # sync once at the end — bool() per parameter serialized the step on
        # one scalar round-trip per tensor (tpu_lint TPL001)
        found_dev = None
        for p in optimizer._parameter_list or []:
            if p.grad is None:
                continue
            g = p.grad._data.astype(jnp.float32) * inv
            bad = jnp.any(~jnp.isfinite(g))
            found_dev = bad if found_dev is None else (found_dev | bad)
            p.grad._data = g.astype(p.grad._data.dtype)
        # the skip/keep decision is a host branch, so one sync is the contract
        # tpu-lint: disable=TPL001 -- single scalar sync per unscale_ by design
        found = bool(found_dev) if found_dev is not None else False
        state["unscaled"] = True
        state["found_inf"] = found
        # update() adjusts the scale off the union of inf sightings this round
        self._found_inf = self._found_inf or found

    @no_grad()
    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        state = self._opt_state(optimizer)
        if not state["unscaled"]:
            self.unscale_(optimizer)
        if not state["found_inf"]:
            optimizer.step()
        state["unscaled"] = False
        state["found_inf"] = False

    def update(self):
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False  # fresh round of inf sightings

    def minimize(self, optimizer, loss):
        # reference pattern is `scaled.backward(); scaler.minimize(opt, scaled)` —
        # minimize must reuse existing .grad, only running backward if it hasn't
        # already run on `loss` (tracked directly, robust to retain_graph=True)
        node = getattr(loss, "_grad_node", None)
        if node is not None and node.vjp_fn is not None \
                and not getattr(loss, "_backward_ran", False):
            loss.backward()
        self.step(optimizer)
        self.update()

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "incr_count": self._good_steps,
                "decr_count": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)


AmpScaler = GradScaler
