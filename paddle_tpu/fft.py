"""paddle.fft — discrete Fourier transforms.

Reference parity: `python/paddle/fft.py` (fft_c2c/c2r/r2c kernels in
`phi/kernels/fft_*`).  TPU-native: every transform lowers to XLA's FFT HLO via
jnp.fft; calls dispatch through `core.tensor.apply` so they record on the eager
tape and run under `to_static` capture.  `norm` semantics ("backward" | "ortho"
| "forward") match numpy/reference.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import apply


def _norm(norm):
    if norm is None:
        return "backward"
    if norm not in ("backward", "ortho", "forward"):
        raise ValueError(f"Unexpected norm: {norm!r}; expected 'forward', "
                         "'backward' or 'ortho'")
    return norm


def _wrap1(jfn, opname):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        nm = _norm(norm)
        return apply(opname, lambda a: jfn(a, n=n, axis=axis, norm=nm), x)
    op.__name__ = opname
    return op


def _wrap2(jfn, opname):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        nm = _norm(norm)
        return apply(opname, lambda a: jfn(a, s=s, axes=axes, norm=nm), x)
    op.__name__ = opname
    return op


def _wrapn(jfn, opname):
    def op(x, s=None, axes=None, norm="backward", name=None):
        nm = _norm(norm)
        return apply(opname, lambda a: jfn(a, s=s, axes=axes, norm=nm), x)
    op.__name__ = opname
    return op


fft = _wrap1(jnp.fft.fft, "fft")
ifft = _wrap1(jnp.fft.ifft, "ifft")
rfft = _wrap1(jnp.fft.rfft, "rfft")
irfft = _wrap1(jnp.fft.irfft, "irfft")
hfft = _wrap1(jnp.fft.hfft, "hfft")
ihfft = _wrap1(jnp.fft.ihfft, "ihfft")

fft2 = _wrap2(jnp.fft.fft2, "fft2")
ifft2 = _wrap2(jnp.fft.ifft2, "ifft2")
rfft2 = _wrap2(jnp.fft.rfft2, "rfft2")
irfft2 = _wrap2(jnp.fft.irfft2, "irfft2")

fftn = _wrapn(jnp.fft.fftn, "fftn")
ifftn = _wrapn(jnp.fft.ifftn, "ifftn")
rfftn = _wrapn(jnp.fft.rfftn, "rfftn")
irfftn = _wrapn(jnp.fft.irfftn, "irfftn")


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """Hermitian n-dim FFT (ref fft.py hfftn): jnp.fft lacks hfftn, so compose
    the hermitian c2r transform along the last axis with c2c on the rest."""
    nm = _norm(norm)

    def f(a):
        if axes is None:
            # numpy semantics: with s given, default to the last len(s) dims
            ax = tuple(range(a.ndim)) if s is None \
                else tuple(range(a.ndim - len(s), a.ndim))
        else:
            ax = tuple(axes)
        other = ax[:-1]
        out = jnp.fft.ifftn(a, s=None if s is None else s[:-1], axes=other,
                            norm=nm) if other else a
        return jnp.fft.hfft(out, n=None if s is None else s[-1], axis=ax[-1],
                            norm=nm)
    return apply("hfftn", f, x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    nm = _norm(norm)

    def f(a):
        if axes is None:
            ax = tuple(range(a.ndim)) if s is None \
                else tuple(range(a.ndim - len(s), a.ndim))
        else:
            ax = tuple(axes)
        out = jnp.fft.ihfft(a, n=None if s is None else s[-1], axis=ax[-1],
                            norm=nm)
        other = ax[:-1]
        return jnp.fft.fftn(out, s=None if s is None else s[:-1], axes=other,
                            norm=nm) if other else out
    return apply("ihfftn", f, x)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s, axes, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s, axes, norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), x)


__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft", "irfft",
           "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft", "hfft2",
           "ihfft2", "hfftn", "ihfftn", "fftfreq", "rfftfreq", "fftshift",
           "ifftshift"]
