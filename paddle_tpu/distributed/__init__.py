"""paddle.distributed parity surface (TPU-native: XLA collectives + GSPMD meshes)."""
from .parallel_env import ParallelEnv, get_rank, get_world_size, init_parallel_env  # noqa
from .communication import (Group, ReduceOp, all_gather, all_gather_object,  # noqa
                            all_reduce, alltoall, alltoall_single, barrier,
                            broadcast, broadcast_object_list, destroy_process_group,
                            gather, get_backend, get_group, irecv, is_initialized,
                            isend, new_group, recv, reduce, reduce_scatter, scatter,
                            scatter_object_list, send, wait, P2POp, batch_isend_irecv,
                            stream)
from .parallel import DataParallel  # noqa
from . import fleet  # noqa
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa
from . import auto_parallel  # noqa
from .auto_parallel.api import shard_tensor, shard_op, dtensor_from_fn, reshard  # noqa
from .auto_parallel.process_mesh import ProcessMesh  # noqa
from .spawn import spawn  # noqa


def is_available():
    return True
