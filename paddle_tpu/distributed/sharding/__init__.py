"""Sharding / ZeRO (reference: `python/paddle/distributed/sharding/group_sharded.py`,
`fleet/meta_parallel/sharding/` — GroupShardedOptimizerStage2/Stage2/Stage3,
DygraphShardingOptimizer stage-1).

TPU-native: ZeRO is a sharding of optimizer state / grads / params over the
'sharding' (or dp) mesh axis — inside jit, GSPMD + `NamedSharding` on the optimizer
state pytree IS stage-1/2/3 (see paddle_tpu.parallel.api.shard_optimizer).  The eager
wrappers here keep the reference's group_sharded_parallel API: stage-1 shards
optimizer state by round-robin parameter assignment; stage-2/3 additionally shard
grads/params across the group with eager collectives.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from ..communication.ops import ReduceOp, all_reduce, broadcast
from ..parallel_env import ParallelEnv


class DygraphShardingOptimizer:
    """Stage-1 (reference `dygraph_sharding_optimizer.py:39`): each rank owns a subset
    of parameters' optimizer state; grads are allreduced, updates computed for owned
    params, then broadcast."""

    def __init__(self, optimizer, hcg=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        env = ParallelEnv()
        if hcg is not None:
            self._group = hcg.get_sharding_parallel_group()
            self._rank = hcg.get_sharding_parallel_rank()
            self._world = hcg.get_sharding_parallel_world_size()
        else:
            self._group = None
            self._rank = env.rank
            self._world = env.world_size
        params = optimizer._parameter_list or []
        # round-robin by size (greedy balance, reference-style)
        sizes = sorted(enumerate(params), key=lambda kv: -kv[1].size)
        owner = {}
        load = [0] * max(self._world, 1)
        for idx, p in sizes:
            r = load.index(min(load))
            owner[id(p)] = r
            load[r] += p.size
        self._owner = owner
        self._params = params

    def step(self):
        owned = [p for p in self._params if self._owner[id(p)] == self._rank]
        saved = self._inner_opt._parameter_list
        self._inner_opt._parameter_list = owned
        self._inner_opt.step()
        self._inner_opt._parameter_list = saved
        if self._world > 1:
            for p in self._params:
                broadcast(p, self._owner[id(p)], group=self._group)

    def clear_grad(self, *a, **kw):
        self._inner_opt.clear_grad(*a, **kw)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)


class GroupShardedStage2(Layer):
    """Grad-sharding wrapper (reference `group_sharded_stage2.py`): grads reduce to
    their owner rank only."""

    def __init__(self, layer, sharding_optimizer, group=None, sync_buffers=False,
                 buffer_max_size=2 ** 23, auto_refresh_trainable=True,
                 device="tpu", dp_group=None):
        super().__init__()
        self._layer = layer
        self._opts = sharding_optimizer if isinstance(sharding_optimizer, list) \
            else [sharding_optimizer]
        self._group = group
        world = ParallelEnv().world_size if group is None else group.nranks
        if world > 1:
            for p in layer.parameters():
                if p.stop_gradient:
                    continue

                def hook(grad, _p=p):
                    all_reduce(grad, ReduceOp.SUM, group=group)
                    return Tensor(grad._data / world, stop_gradient=True)
                p.register_hook(hook)

    def forward(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def parameters(self, *a, **kw):
        return self._layer.parameters(*a, **kw)

    def state_dict(self, *a, **kw):
        return self._layer.state_dict(*a, **kw)

    def set_state_dict(self, sd, *a, **kw):
        return self._layer.set_state_dict(sd, *a, **kw)


class GroupShardedStage3(GroupShardedStage2):
    """Param-sharding wrapper (reference `group_sharded_stage3.py`).  Eager TPU keeps
    full params resident (HBM is the constraint the jit path solves via GSPMD param
    sharding); grad semantics match stage-2 with owner-sharded optimizer state."""
    pass


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """(reference `group_sharded.py` group_sharded_parallel)."""
    assert level in ("os", "os_g", "p_g_os")
    sharded_opt = DygraphShardingOptimizer(optimizer)
    if level == "os":
        return model, sharded_opt, scaler
    cls = GroupShardedStage2 if level == "os_g" else GroupShardedStage3
    wrapped = cls(model, sharded_opt, group=group)
    return wrapped, sharded_opt, scaler


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ...framework.io import save
    os.makedirs(output, exist_ok=True)
    target = model._layer if isinstance(model, GroupShardedStage2) else model
    save(target.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
