"""Sharding / ZeRO (reference: `python/paddle/distributed/sharding/group_sharded.py`,
`fleet/meta_parallel/sharding/` — GroupShardedOptimizerStage2/Stage2/Stage3,
DygraphShardingOptimizer stage-1).

TPU-native: ZeRO is a sharding of optimizer state / grads / params over the
'sharding' (or dp) mesh axis — inside jit, GSPMD + `NamedSharding` on the optimizer
state pytree IS stage-1/2/3 (see paddle_tpu.parallel.api.shard_optimizer).  The eager
wrappers here keep the reference's group_sharded_parallel API: stage-1 shards
optimizer state by round-robin parameter assignment; stage-2/3 additionally shard
grads/params across the group with eager collectives.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...nn.clip import ClipGradByGlobalNorm as _ClipGradByGlobalNorm
from ...nn.layer.layers import Layer
from ..communication.ops import ReduceOp, all_reduce, broadcast
from ..parallel_env import ParallelEnv


class DygraphShardingOptimizer:
    """Stage-1 (reference `dygraph_sharding_optimizer.py:39`): each rank owns a subset
    of parameters' optimizer state; grads are allreduced, updates computed for owned
    params, then broadcast."""

    def __init__(self, optimizer, hcg=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        env = ParallelEnv()
        if hcg is not None:
            self._group = hcg.get_sharding_parallel_group()
            self._rank = hcg.get_sharding_parallel_rank()
            self._world = hcg.get_sharding_parallel_world_size()
        else:
            self._group = None
            self._rank = env.rank
            self._world = env.world_size
        params = optimizer._parameter_list or []
        # round-robin by size (greedy balance, reference-style)
        sizes = sorted(enumerate(params), key=lambda kv: -kv[1].size)
        owner = {}
        load = [0] * max(self._world, 1)
        for idx, p in sizes:
            r = load.index(min(load))
            owner[id(p)] = r
            load[r] += p.size
        self._owner = owner
        self._params = params

    def step(self):
        owned = [p for p in self._params if self._owner[id(p)] == self._rank]
        saved = self._inner_opt._parameter_list
        self._inner_opt._parameter_list = owned
        self._inner_opt.step()
        self._inner_opt._parameter_list = saved
        if self._world > 1:
            for p in self._params:
                broadcast(p, self._owner[id(p)], group=self._group)

    def clear_grad(self, *a, **kw):
        self._inner_opt.clear_grad(*a, **kw)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)


class GroupShardedStage2(Layer):
    """Grad-sharding wrapper (reference `group_sharded_stage2.py`): each grad
    reduces to its OWNER rank only (not all-reduced to every rank), halving grad
    traffic vs plain DP and leaving non-owners free to drop the buffer."""

    def __init__(self, layer, sharding_optimizer, group=None, sync_buffers=False,
                 buffer_max_size=2 ** 23, auto_refresh_trainable=True,
                 device="tpu", dp_group=None):
        super().__init__()
        self._layer = layer
        opts = sharding_optimizer if isinstance(sharding_optimizer, list) \
            else [sharding_optimizer]
        self._opts = opts
        self._group = group
        env = ParallelEnv()
        self._rank = env.rank if group is None else group.get_group_rank(env.rank)
        world = env.world_size if group is None else group.nranks
        self._world = world
        # owner map from the stage-1 optimizer (round-robin-by-size)
        owner = {}
        for o in opts:
            if isinstance(o, DygraphShardingOptimizer):
                owner.update(o._owner)
        if world > 1:
            for p in layer.parameters():
                if p.stop_gradient:
                    continue
                dst = owner.get(id(p), 0)

                def hook(grad, _dst=dst):
                    from ..communication.ops import reduce as _reduce
                    _reduce(grad, _dst, ReduceOp.SUM, group=group)
                    if self._rank != _dst:
                        # non-owner: grad is dead weight (owner updates + later
                        # broadcasts the param) — release it
                        return Tensor(jnp.zeros((), grad._data.dtype),
                                      stop_gradient=True)
                    return Tensor(grad._data / self._world, stop_gradient=True)
                p.register_hook(hook)

    def forward(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def parameters(self, *a, **kw):
        return self._layer.parameters(*a, **kw)

    def state_dict(self, *a, **kw):
        return self._layer.state_dict(*a, **kw)

    def set_state_dict(self, sd, *a, **kw):
        return self._layer.set_state_dict(sd, *a, **kw)


class GroupShardedStage3(Layer):
    """Param-sharding wrapper (reference `group_sharded_stage3.py`): each rank
    stores a flat 1/world slice of every parameter; the full tensor is gathered
    on demand at forward entry and released (re-sliced) after the step; grads
    reduce-scatter so each rank keeps only its slice's grad, and optimizer state
    is built on slices.  world==1 degrades to a plain pass-through."""

    def __init__(self, layer, optimizer, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 20, pertrain_sync_models=True,
                 offload=False, sync_comm=False, dp_group=None,
                 exclude_layer=None):
        super().__init__()
        self._layer = layer
        self._opts = optimizer if isinstance(optimizer, list) else [optimizer]
        self._group = group
        env = ParallelEnv()
        self._rank = env.rank if group is None else group.get_group_rank(env.rank)
        self._world = env.world_size if group is None else group.nranks
        # offload (ref group_sharded offload=True): the resident param slice
        # (and therefore the optimizer state built on it) lives on HOST memory;
        # gather stages it back to the accelerator.  Updates on offloaded
        # slices execute on the CPU backend, like the reference's CPU adam.
        self._offload = offload
        self._host = None
        if offload:
            import jax
            try:
                self._host = jax.local_devices(backend="cpu")[0]
            except RuntimeError:
                self._host = None  # no CPU backend: offload degrades to no-op
        self._registry = []  # (param, full_shape, padded_len)
        if self._world > 1:
            for p in layer.parameters():
                if p.stop_gradient:
                    continue
                full_shape = tuple(p._data.shape)
                n = int(np.prod(full_shape)) if full_shape else 1
                pad = (-n) % self._world
                self._registry.append((p, full_shape, n + pad))
                self._reshard_param(p, full_shape, n + pad)
                p.register_hook(self._make_grad_hook(full_shape, n + pad))

    # ---- shard/gather primitives ----
    def _reshard_param(self, p, full_shape, padded):
        import jax
        chunk = padded // self._world
        flat = jnp.ravel(p._data)
        flat = jnp.pad(flat, (0, padded - flat.size))
        sl = flat[self._rank * chunk:(self._rank + 1) * chunk]
        if self._offload and self._host is not None:
            sl = jax.device_put(sl, self._host)
        p._data = sl

    def _gather_param(self, p, full_shape, padded):
        import jax
        from ..communication.ops import all_gather
        local = p._data
        if self._offload and self._host is not None:
            local = jax.device_put(local, jax.local_devices()[0])  # to device
        pieces = []
        all_gather(pieces, Tensor(local, stop_gradient=True), group=self._group)
        flat = jnp.concatenate([t._data for t in pieces])
        n = int(np.prod(full_shape)) if full_shape else 1
        p._data = flat[:n].reshape(full_shape)

    def _make_grad_hook(self, full_shape, padded):
        def hook(grad):
            from ..communication.ops import reduce_scatter
            chunk = padded // self._world
            flat = jnp.ravel(grad._data)
            flat = jnp.pad(flat, (0, padded - flat.size)) / self._world
            parts = [Tensor(flat[r * chunk:(r + 1) * chunk], stop_gradient=True)
                     for r in range(self._world)]
            out = Tensor(jnp.zeros((chunk,), flat.dtype), stop_gradient=True)
            reduce_scatter(out, parts, ReduceOp.SUM, group=self._group)
            return out
        return hook

    def forward(self, *args, **kwargs):
        for p, shape, padded in self._registry:
            self._gather_param(p, shape, padded)
        out = self._layer(*args, **kwargs)
        # full values live on in the autograd closures until backward completes;
        # the resident storage drops back to the slice immediately
        for p, shape, padded in self._registry:
            self._reshard_param(p, shape, padded)
        return out

    def get_all_parameters(self):
        """Materialize full parameters on every rank (reference API)."""
        for p, shape, padded in self._registry:
            self._gather_param(p, shape, padded)

    def parameters(self, *a, **kw):
        return self._layer.parameters(*a, **kw)

    def state_dict(self, *a, **kw):
        if self._world > 1:
            self.get_all_parameters()
            sd = self._layer.state_dict(*a, **kw)
            # snapshot values while FULL: sd entries are the live params, whose
            # storage drops back to the slice on the reshard below
            sd = {k: Tensor(v._data, stop_gradient=True)
                  if isinstance(v, Tensor) else v for k, v in sd.items()}
            for p, shape, padded in self._registry:
                self._reshard_param(p, shape, padded)
            return sd
        return self._layer.state_dict(*a, **kw)

    def set_state_dict(self, sd, *a, **kw):
        if self._world > 1:
            # live params are 1-D slices; materialize full shapes so the
            # full-shape checkpoint loads, then drop back to slices
            self.get_all_parameters()
        res = self._layer.set_state_dict(sd, *a, **kw)
        for p, shape, padded in self._registry:
            self._reshard_param(p, shape, padded)
        return res


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """(reference `group_sharded.py` group_sharded_parallel)."""
    assert level in ("os", "os_g", "p_g_os")
    if level != "p_g_os" and offload:
        import warnings
        warnings.warn("group_sharded_parallel: offload is implemented for "
                      "level='p_g_os' only; levels os/os_g keep state on the "
                      "accelerator")
    if level == "p_g_os":
        # stage 3: every rank owns a 1/world SLICE of every param, so every
        # rank steps all its slice-params with the raw optimizer — the stage-1
        # owner/broadcast split would overwrite other ranks' slices
        from ...nn.clip import ClipGradByGlobalNorm, ClipGradByNorm
        clip = getattr(optimizer, "_grad_clip", None)
        if isinstance(clip, ClipGradByGlobalNorm):
            # each rank sees only slice grads: the squared norm must reduce
            # across the sharding group before clipping (ref stage-3 clip)
            optimizer._grad_clip = _ShardedClipGradByGlobalNorm(
                clip.clip_norm, group)
        elif isinstance(clip, ClipGradByNorm):
            raise NotImplementedError(
                "ClipGradByNorm under stage-3 would clip per-SLICE norms and "
                "silently diverge from serial; use ClipGradByGlobalNorm")
        wrapped = GroupShardedStage3(model, optimizer, group=group,
                                     offload=offload)
        return wrapped, optimizer, scaler
    sharded_opt = DygraphShardingOptimizer(optimizer)
    if level == "os":
        return model, sharded_opt, scaler
    wrapped = GroupShardedStage2(model, sharded_opt, group=group)
    return wrapped, sharded_opt, scaler


class _ShardedClipGradByGlobalNorm(_ClipGradByGlobalNorm):
    """ClipGradByGlobalNorm over slice-sharded grads: the squared norm is
    all-reduced across the sharding group so every rank clips with the TRUE
    global norm (ref group_sharded clip).  Subclassing keeps _need_clip
    semantics and isinstance checks (e.g. HybridParallelOptimizer's)."""

    def __init__(self, clip_norm, group=None):
        super().__init__(clip_norm)
        self._group = group

    def _reduce_global_norm_sq(self, global_norm):
        t = Tensor(jnp.square(global_norm)[None], stop_gradient=True)
        all_reduce(t, ReduceOp.SUM, group=self._group)
        return jnp.sqrt(t._data[0])


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ...framework.io import save
    os.makedirs(output, exist_ok=True)
    target = model._layer if isinstance(model, GroupShardedStage2) else model
    save(target.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
