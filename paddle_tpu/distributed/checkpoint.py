"""Distributed checkpoint save/load with cross-mesh resharding.

Reference parity: `python/paddle/distributed/auto_parallel/static/dist_saver.py`
(per-rank shard files + dist_attr metadata) and `converter.py` (re-shard a saved
checkpoint onto a different mesh/parallel config).

TPU-native design: each leaf is written as one logical array plus its layout
metadata (mesh axes + PartitionSpec).  jax global arrays know their own
sharding, so "merge shards" is `np.asarray` on the global array (XLA gathers
over ICI), and resharding on load is a single `device_put` with the target
NamedSharding — the converter's slice/concat machinery collapses into the
runtime's layout transfer.  Multi-host: every host holds the full logical
array file; `device_put` lays out only the local shards.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def _spec_to_meta(sharding) -> Any:
    if isinstance(sharding, NamedSharding):
        return [list(e) if isinstance(e, tuple) else e for e in sharding.spec]
    return None


def _meta_to_spec(meta) -> P:
    if meta is None:
        return P()
    return P(*[tuple(e) if isinstance(e, list) else e for e in meta])


def save_state_dict(state: Dict[str, Any], path: str) -> None:
    """Save a (possibly sharded) pytree of jax arrays + layout metadata.

    Layout: <path>/data.npz (full logical arrays) + <path>/dist_attr.json
    (per-leaf PartitionSpec + source mesh shape/axes — the dist_attr file of
    the reference's dist_saver)."""
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    os.makedirs(path, exist_ok=True)
    arrays = {}
    meta = {"leaves": {}, "mesh": None, "dtypes": {}}
    for keypath, leaf in flat:
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in keypath)
        a = np.asarray(leaf)                 # gathers shards over ICI
        # npz round-trips ml_dtypes (bfloat16, float8_*) as raw void — record
        # the dtype name and store a same-width uint bit-view instead (the
        # reference dist_saver preserves dtype in its metadata the same way)
        if a.dtype.kind == "V" or a.dtype.name not in np.sctypeDict:
            meta["dtypes"][name] = a.dtype.name
            a = a.view(np.dtype(f"u{a.dtype.itemsize}"))
        arrays[name] = a
        sh = getattr(leaf, "sharding", None)
        meta["leaves"][name] = _spec_to_meta(sh)
        if isinstance(sh, NamedSharding) and meta["mesh"] is None:
            meta["mesh"] = {"axes": list(sh.mesh.axis_names),
                            "shape": [int(s) for s in sh.mesh.devices.shape]}
    np.savez(os.path.join(path, "data.npz"), **arrays)
    with open(os.path.join(path, "dist_attr.json"), "w") as f:
        json.dump(meta, f, indent=1)
    with open(os.path.join(path, "treedef.pkl"), "wb") as f:
        pickle.dump(jax.tree_util.tree_structure(state), f)


def load_state_dict(path: str, target_shardings=None, template=None):
    """Load a checkpoint, resharding every leaf onto `target_shardings`
    (a matching pytree of NamedShardings, or None for host arrays).

    The target mesh may differ arbitrarily from the saving mesh — this is the
    reference converter's cross-mesh resume."""
    data = np.load(os.path.join(path, "data.npz"))
    try:
        with open(os.path.join(path, "dist_attr.json")) as f:
            saved_dtypes = json.load(f).get("dtypes", {})
    except FileNotFoundError:
        saved_dtypes = {}
    with open(os.path.join(path, "treedef.pkl"), "rb") as f:
        treedef = pickle.load(f)
    # rebuild leaves in treedef order
    names = []
    dummy = jax.tree_util.tree_unflatten(
        treedef, list(range(treedef.num_leaves)))
    flat = jax.tree_util.tree_flatten_with_path(dummy)[0]
    order = [None] * treedef.num_leaves
    for keypath, idx in flat:
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in keypath)
        order[idx] = name
        names.append(name)
    def _restore(n):
        a = data[n]
        if n in saved_dtypes:
            import ml_dtypes
            a = a.view(np.dtype(getattr(ml_dtypes, saved_dtypes[n])))
        return a

    leaves = [_restore(n) for n in order]
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    if target_shardings is not None:
        state = jax.tree_util.tree_map(
            lambda a, sh: jax.device_put(a, sh) if sh is not None else a,
            state, target_shardings,
            is_leaf=lambda x: isinstance(x, np.ndarray))
    return state


def saved_dist_attr(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, "dist_attr.json")) as f:
        return json.load(f)
