"""Launch auto-tuner: search the parallelism space by timed trial runs.

Reference parity: `python/paddle/distributed/auto_tuner/` (`tuner.py:19` —
grid candidates over dp/mp/pp/sharding/micro-batch, `prune.py` rule-based
pruning, trial launches scored by throughput).

TPU-native: a "trial launch" is just building a HybridParallelTrainer on the
mesh and timing a few steps — no subprocess relaunch needed, so the whole
search runs in-process (on the virtual CPU mesh in CI, on the pod in prod).
"""
from __future__ import annotations

import dataclasses
import itertools
import time
from typing import List, Optional

import numpy as np


@dataclasses.dataclass
class TrialResult:
    cfg: "object"
    tokens_per_sec: float
    error: Optional[str] = None

    @property
    def ok(self):
        return self.error is None


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def generate_candidates(n_devices: int, model_config, max_mp=8, max_pp=8,
                        micro_batches=(1, 2, 4), use_sharding=True):
    """All (dp, mp, pp, sharding, micro) factorizations that survive the
    pruning rules (ref prune.py):
    - mp divides num_heads and hidden_size, mp <= max_mp
    - pp divides num_layers, pp <= max_pp; micro % pp == 0 when pp > 1
    - sharding only as a dp-replacement axis (ZeRO), stage from degree
    """
    from ...parallel import MeshConfig
    cands = []
    for mp in _divisors(n_devices):
        if mp > max_mp or model_config.num_heads % mp or \
                model_config.hidden_size % mp:
            continue
        rem = n_devices // mp
        for pp in _divisors(rem):
            if pp > max_pp or model_config.num_layers % pp:
                continue
            rem2 = rem // pp
            shard_opts = [(rem2, 1), (1, rem2)] if use_sharding and rem2 > 1 \
                else [(rem2, 1)]
            for dp, sh in shard_opts:
                for mb in micro_batches:
                    if pp > 1 and mb % pp:
                        continue
                    if pp == 1 and mb != micro_batches[0]:
                        continue  # micro only matters with pp
                    cands.append(MeshConfig(
                        dp=dp, pp=pp, sharding=sh, mp=mp,
                        sharding_stage=2 if sh > 1 else 1,
                        micro_batches=mb if pp > 1 else 1,
                        remat=True))
    # dedupe
    seen, out = set(), []
    for c in cands:
        key = (c.dp, c.pp, c.sharding, c.mp, c.micro_batches)
        if key not in seen:
            seen.add(key)
            out.append(c)
    return out


class AutoTuner:
    """ref tuner.py AutoTuner: iterate candidates, run trials, rank."""

    def __init__(self, model_config, devices=None, batch=None, seq=None,
                 trial_steps=3, candidates=None, verbose=False):
        import jax
        self.model_config = model_config
        self.devices = devices if devices is not None else jax.devices()
        self.batch = batch
        self.seq = seq or min(model_config.max_seq_len, 128)
        self.trial_steps = trial_steps
        self.candidates = candidates
        self.verbose = verbose
        self.results: List[TrialResult] = []

    def _trial(self, cfg) -> TrialResult:
        from ...parallel import HybridParallelTrainer
        mc = self.model_config
        B = self.batch or max(2 * cfg.dp * cfg.sharding * cfg.ep *
                              max(cfg.micro_batches, 1), 4)
        rng = np.random.RandomState(0)
        tok = rng.randint(0, mc.vocab_size, (B, self.seq)).astype(np.int32)
        lab = np.roll(tok, -1, axis=1).astype(np.int32)
        try:
            tr = HybridParallelTrainer(mc, cfg, devices=self.devices[:cfg.size])
            float(tr.train_step(tok, lab))          # compile + warmup
            t0 = time.perf_counter()
            for _ in range(self.trial_steps):
                loss = tr.train_step(tok, lab)
            f = float(loss)
            dt = time.perf_counter() - t0
            if not np.isfinite(f):
                return TrialResult(cfg, 0.0, "non-finite loss")
            return TrialResult(cfg, B * self.seq * self.trial_steps / dt)
        except Exception as e:  # OOM / invalid combo: prune, keep searching
            return TrialResult(cfg, 0.0, str(e)[:200])

    def search(self):
        cands = self.candidates or generate_candidates(
            len(self.devices), self.model_config)
        self.results = []
        for cfg in cands:
            r = self._trial(cfg)
            self.results.append(r)
            if self.verbose:
                state = f"{r.tokens_per_sec:.0f} tok/s" if r.ok \
                    else f"pruned: {r.error[:60]}"
                print(f"[auto_tuner] dp={cfg.dp} mp={cfg.mp} pp={cfg.pp} "
                      f"sharding={cfg.sharding} micro={cfg.micro_batches}: "
                      f"{state}", flush=True)
        ok = [r for r in self.results if r.ok]
        if not ok:
            raise RuntimeError("auto_tuner: every candidate failed; last "
                               f"error: {self.results[-1].error}")
        return max(ok, key=lambda r: r.tokens_per_sec)


def tune(model_config, devices=None, **kwargs):
    """One-call tuning: returns (best MeshConfig, all TrialResults)."""
    t = AutoTuner(model_config, devices=devices, **kwargs)
    best = t.search()
    return best.cfg, t.results


__all__ = ["AutoTuner", "TrialResult", "generate_candidates", "tune"]
