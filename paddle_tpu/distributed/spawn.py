"""dist.spawn (reference: `python/paddle/distributed/spawn.py:428`)."""
from __future__ import annotations

import multiprocessing
import os


def _wrap(func, rank, nprocs, master, args):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_MASTER"] = master
    endpoints = [f"127.0.0.1:{int(master.split(':')[1]) + i}" for i in range(nprocs)]
    os.environ["PADDLE_TRAINER_ENDPOINTS"] = ",".join(endpoints)
    os.environ["PADDLE_CURRENT_ENDPOINT"] = endpoints[rank]
    func(*args)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    if nprocs < 1:
        nprocs = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    master = f"127.0.0.1:{port}"
    ctx = multiprocessing.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_wrap, args=(func, rank, nprocs, master, args),
                        daemon=daemon)
        p.start()
        procs.append(p)

    class Context:
        def __init__(self, processes):
            self.processes = processes

        def join(self):
            for p in self.processes:
                p.join()

    c = Context(procs)
    if join:
        c.join()
    return c
