"""Launch CLI (reference: `python/paddle/distributed/launch/main.py:18`,
`controllers/collective.py` — node/device discovery, rendezvous, Pod of Containers,
watch loop with elastic relaunch).

TPU-native: one trainer process per host drives all local chips (XLA model), so
`--nproc_per_node` defaults to 1 on TPU hosts (the reference's per-GPU process model
is preserved for CPU simulation with N>1).  Rendezvous uses the reference's env-var
contract (PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM/PADDLE_MASTER/...); the coordination
service behind it is jax.distributed (see parallel_env).
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class Container:
    """One trainer process (reference `launch/job/container.py`)."""

    def __init__(self, rank, cmd, env, log_dir):
        self.rank = rank
        self.cmd = cmd
        self.env = env
        self.log_dir = log_dir
        self.proc = None
        self.log_file = None

    def start(self):
        os.makedirs(self.log_dir, exist_ok=True)
        path = os.path.join(self.log_dir, f"workerlog.{self.rank}")
        self.log_file = open(path, "ab")
        self.proc = subprocess.Popen(self.cmd, env=self.env, stdout=self.log_file,
                                     stderr=subprocess.STDOUT)

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    @property
    def returncode(self):
        return self.proc.poll() if self.proc else None

    def terminate(self):
        if self.alive():
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        if self.log_file:
            self.log_file.close()


class CollectiveController:
    """(reference `controllers/collective.py:22`)."""

    def __init__(self, args, training_args):
        self.args = args
        self.training_args = training_args
        self.containers = []

    def build_pod(self):
        n = self.args.nproc_per_node
        master = self.args.master or f"127.0.0.1:{_free_port()}"
        endpoints = []
        host, _, mport = master.partition(":")
        for i in range(n):
            endpoints.append(f"{host}:{int(mport) + i}")
        base_env = dict(os.environ)
        for rank in range(n):
            env = dict(base_env)
            env.update({
                "PADDLE_TRAINER_ID": str(rank + self.args.rank * n),
                "PADDLE_TRAINERS_NUM": str(n * self.args.nnodes),
                "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
                "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
                "PADDLE_MASTER": master,
                "PADDLE_LOCAL_RANK": str(rank),
                "PADDLE_LOCAL_SIZE": str(n),
                "FLAGS_selected_tpus": str(rank),
            })
            if self.args.devices:
                env["CUDA_VISIBLE_DEVICES"] = self.args.devices
            cmd = [sys.executable] + ([self.args.training_script]
                                      if not self.args.module
                                      else ["-m", self.args.training_script]) \
                + self.training_args
            self.containers.append(Container(rank, cmd, env, self.args.log_dir))

    def run(self):
        from ..fleet.elastic import ElasticManager, ElasticStatus
        n0 = self.args.nproc_per_node
        mgr = ElasticManager(self.args.np or str(n0), timeout=10.0,
                             max_restart=self.args.max_restart)

        self.build_pod()
        for c in self.containers:
            c.start()
            mgr.register(c.rank)
        print(f"[launch] started {len(self.containers)} trainer(s); "
              f"logs in {self.args.log_dir}")

        def handler(sig, frame):
            for c in self.containers:
                c.terminate()
            sys.exit(1)

        signal.signal(signal.SIGINT, handler)
        signal.signal(signal.SIGTERM, handler)

        while True:
            time.sleep(1)
            # process liveness IS the heartbeat (ref: etcd heartbeats)
            for c in self.containers:
                if c.alive():
                    mgr.heartbeat(c.rank)
            dead = [c for c in self.containers if not c.alive()]
            failed = [c for c in dead if c.returncode != 0]
            if not failed and len(dead) == len(self.containers):
                print("[launch] all trainers finished")
                return 0
            if not failed:
                continue
            for c in failed:
                mgr.report_failure(c.rank)
            status = mgr.decide()
            if status == ElasticStatus.RESTART and self.args.elastic_level > 0 \
                    and mgr.restarts < self.args.max_restart:
                new_n = mgr.scaled_np() if self.args.np else n0
                mgr.on_restart()
                print(f"[launch] trainer failed (rc={failed[0].returncode}); "
                      f"elastic relaunch {mgr.restarts}/{self.args.max_restart} "
                      f"with np={new_n}")
                for c in self.containers:
                    c.terminate()
                self.containers = []
                self.args.nproc_per_node = new_n
                self.build_pod()
                for c in self.containers:
                    c.start()
                    mgr.register(c.rank)
            else:
                print(f"[launch] trainer {failed[0].rank} failed with "
                      f"rc={failed[0].returncode}; terminating pod")
                for c in self.containers:
                    c.terminate()
                return failed[0].returncode or 1


def launch():
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--master", default=None,
                        help="rendezvous endpoint host:port")
    parser.add_argument("--nnodes", type=int,
                        default=int(os.getenv("PADDLE_NNODES", "1")))
    parser.add_argument("--rank", type=int, default=int(os.getenv("PADDLE_RANK", "0")),
                        help="node rank")
    parser.add_argument("--nproc_per_node", type=int,
                        default=int(os.getenv("PADDLE_NPROC_PER_NODE", "1")))
    parser.add_argument("--devices", "--gpus", "--tpus", default=None)
    parser.add_argument("--log_dir", default="log")
    parser.add_argument("--run_mode", default="collective")
    parser.add_argument("--job_id", default="default")
    parser.add_argument("--elastic_level", type=int,
                        default=int(os.getenv("PADDLE_ELASTIC_LEVEL", "0")))
    parser.add_argument("--max_restart", type=int, default=3)
    parser.add_argument("--np", default=os.getenv("PADDLE_ELASTIC_NP"),
                        help="elastic world-size range 'min:max' (ref elastic "
                             "np): on member loss the pod relaunches scaled "
                             "down to the live count within the range")
    parser.add_argument("--module", "-m", action="store_true",
                        help="run training script as a module")
    parser.add_argument("training_script")
    parser.add_argument("training_args", nargs=argparse.REMAINDER)
    args = parser.parse_args()

    ctl = CollectiveController(args, args.training_args)
    sys.exit(ctl.run())


if __name__ == "__main__":
    launch()
