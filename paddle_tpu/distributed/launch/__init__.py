from .main import launch  # noqa
