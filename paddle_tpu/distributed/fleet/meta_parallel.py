"""Meta-parallel wrappers (reference: `fleet/meta_parallel/` — `PipelineLayer`
`parallel_layers/pp_layers.py:239`, `PipelineParallel` `pipeline_parallel.py`,
`TensorParallel`).

TPU-native pipeline: stages are segments of a LayerDesc list (reference SegmentLayers
:92).  Eager multi-process 1F1B with NCCL p2p has no TPU analog — the compiled path
(`paddle_tpu.parallel.pipeline`) runs the microbatch loop inside one jitted program
with `shard_map`+ppermute over the 'pp' mesh axis.  This wrapper keeps the reference's
train_batch API: single-process it runs the full model with microbatch gradient
accumulation (exact 1F1B numerics); multi-process it instructs users to the compiled
engine.
"""
from __future__ import annotations

import re
from typing import List

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from ..parallel import sync_params_buffers


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition N layers into P stages (reference `SegmentLayers` :92)."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self):
        n = len(self.descs)
        if self.method == "uniform":
            base = n // self.num_parts
            rem = n % self.num_parts
            parts = [0]
            for i in range(self.num_parts):
                parts.append(parts[-1] + base + (1 if i < rem else 0))
            return parts
        if self.method.startswith("layer:"):
            pat = self.method.split(":", 1)[1]
            matches = [i for i, d in enumerate(self.descs)
                       if re.search(pat, getattr(d.layer_cls, "__name__", str(d)))]
            per = len(matches) // self.num_parts
            parts = [0]
            for i in range(1, self.num_parts):
                parts.append(matches[i * per])
            parts.append(n)
            return parts
        raise ValueError(f"unknown segment method {self.method}")


class PipelineLayer(Layer):
    """(reference `pp_layers.py:239`)."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, num_virtual_pipeline_stages=None,
                 **kwargs):
        super().__init__()
        from .topology import _get_hybrid_group
        self._loss_fn = loss_fn
        self.descs = list(layers)
        hcg = _get_hybrid_group()
        self._topo = topology
        if num_stages is None:
            num_stages = hcg.get_pipe_parallel_world_size() if hcg else 1
        self._num_stages = num_stages
        self._stage_id = hcg.get_stage_id() if hcg else 0
        self.segment_parts = SegmentLayers(self.descs, num_stages, seg_method).do_segment()
        self._recompute_interval = recompute_interval
        start = self.segment_parts[self._stage_id]
        end = self.segment_parts[self._stage_id + 1]
        self._start, self._end = start, end
        self._shared = {}
        from .container_utils import build_desc_layer
        self.run_function = []
        for i in range(start, end):
            desc = self.descs[i]
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name not in self._shared:
                    self._shared[desc.layer_name] = desc.build_layer()
                lyr = self._shared[desc.layer_name]
                fwd = desc.forward_func
                self.add_sublayer(f"shared_{desc.layer_name}_{i}", lyr)
                if fwd is not None:
                    self.run_function.append(lambda x, l=lyr, f=fwd: f(l, x))
                else:
                    self.run_function.append(lyr)
            elif isinstance(desc, LayerDesc):
                lyr = desc.build_layer()
                self.add_sublayer(str(i), lyr)
                self.run_function.append(lyr)
            elif isinstance(desc, Layer):
                self.add_sublayer(str(i), desc)
                self.run_function.append(desc)
            elif callable(desc):
                self.run_function.append(desc)
            else:
                raise TypeError(f"bad pipeline item {desc}")

    def get_stage_from_index(self, layer_idx):
        for s in range(self._num_stages):
            if self.segment_parts[s] <= layer_idx < self.segment_parts[s + 1]:
                return s
        return self._num_stages - 1

    def forward(self, x):
        from .recompute import recompute
        for i, fn in enumerate(self.run_function):
            if self._recompute_interval > 0 and i % self._recompute_interval == 0 \
                    and isinstance(x, Tensor):
                x = recompute(fn, x)
            else:
                x = fn(x)
        return x


class PipelineParallel(Layer):
    """(reference `pipeline_parallel.py:590` train_batch / :387 1F1B).

    Single-process: microbatched gradient accumulation — numerically identical to 1F1B.
    Multi-process eager: directed to the compiled pipeline engine.
    """

    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pp_cfg = strategy.pipeline_configs if strategy else {}
        self.accumulate_steps = pp_cfg.get("accumulate_steps", 1)
        self.micro_batch_size = pp_cfg.get("micro_batch_size", 1)
        if hcg.get_data_parallel_world_size() > 1:
            sync_params_buffers(layers, hcg.get_data_parallel_group())

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        if self._hcg.get_pipe_parallel_world_size() > 1 and \
                self._hcg.get_mesh().size > len(jax.local_devices()):
            raise RuntimeError(
                "multi-process eager pipeline: use paddle_tpu.parallel.pipeline "
                "(compiled 1F1B over the pp mesh axis)")
        x, y = data
        total = x.shape[0]
        micro = max(total // self.accumulate_steps, 1)
        losses = []
        for i in range(0, total, micro):
            xb = x[i:i + micro]
            yb = y[i:i + micro]
            out = self._layers(xb)
            loss = self._layers._loss_fn(out, yb) if hasattr(self._layers, "_loss_fn") \
                and self._layers._loss_fn else out
            scaled = loss / self.accumulate_steps
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            losses.append(loss)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        from ...ops.manipulation import stack
        from ...ops.math import mean
        return mean(stack([l.detach() for l in losses]))

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x)
        if compute_loss and getattr(self._layers, "_loss_fn", None):
            return self._layers._loss_fn(out, y)
        return out

    def parameters(self, *a, **kw):
        return self._layers.parameters(*a, **kw)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, sd, *a, **kw):
        return self._layers.set_state_dict(sd, *a, **kw)


import jax  # noqa: E402


class TensorParallel(Layer):
    """(reference `meta_parallel/tensor_parallel.py`): broadcast non-distributed params
    within mp group, DP-sync across dp group."""

    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        if hcg.get_model_parallel_world_size() > 1:
            for p in layers.parameters():
                if not getattr(p, "is_distributed", False):
                    from ..communication.ops import broadcast
                    broadcast(p, hcg.get_model_parallel_group_src_rank(),
                              group=hcg.get_model_parallel_group())
        if hcg.get_data_parallel_world_size() > 1:
            sync_params_buffers(layers, hcg.get_data_parallel_group())

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, *a, **kw):
        return self._layers.parameters(*a, **kw)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, sd, *a, **kw):
        return self._layers.set_state_dict(sd, *a, **kw)
