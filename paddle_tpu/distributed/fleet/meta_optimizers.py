"""HybridParallelOptimizer (reference:
`fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:251`) and the
hybrid grad scaler."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.clip import ClipGradByGlobalNorm
from ..communication.ops import ReduceOp, all_reduce


class _HybridGlobalNormClip(ClipGradByGlobalNorm):
    """Global-norm clip whose squared-norm sum is reduced across mp/pp/sharding groups
    (reference `_dygraph_clip` in hybrid_parallel_optimizer)."""

    def __init__(self, inner: ClipGradByGlobalNorm, hcg):
        super().__init__(inner.clip_norm)
        self._hcg = hcg

    def _reduce_global_norm_sq(self, global_norm):
        sq = Tensor(jnp.square(global_norm))
        if self._hcg.get_model_parallel_world_size() > 1:
            all_reduce(sq, ReduceOp.SUM, group=self._hcg.get_model_parallel_group())
        if self._hcg.get_pipe_parallel_world_size() > 1:
            all_reduce(sq, ReduceOp.SUM, group=self._hcg.get_pipe_parallel_group())
        if self._hcg.get_sharding_parallel_world_size() > 1:
            all_reduce(sq, ReduceOp.SUM, group=self._hcg.get_sharding_parallel_group())
        return jnp.sqrt(sq._data)


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        clip = optimizer._grad_clip
        if isinstance(clip, ClipGradByGlobalNorm):
            optimizer._grad_clip = _HybridGlobalNormClip(clip, hcg)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **kw):
        self._inner_opt.clear_grad(*a, **kw)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **kw):
        return self._inner_opt.minimize(loss, *a, **kw)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def set_lr(self, v):
        return self._inner_opt.set_lr(v)

    def get_lr(self):
        return self._inner_opt.get_lr()

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)


class HybridParallelGradScaler:
    """(reference `hybrid_parallel_gradscaler.py`): found_inf is reduced across the
    hybrid groups before the skip decision."""

    def __init__(self, scaler, hcg):
        self._scaler = scaler
        self._hcg = hcg

    def scale(self, var):
        return self._scaler.scale(var)

    def step(self, optimizer):
        inner = optimizer._inner_opt if isinstance(optimizer, HybridParallelOptimizer) \
            else optimizer
        if self._scaler._enable:
            self._scaler.unscale_(inner)
            found = Tensor(jnp.asarray([1.0 if self._scaler._found_inf else 0.0]))
            if self._hcg and self._hcg.get_model_parallel_world_size() > 1:
                all_reduce(found, ReduceOp.MAX, group=self._hcg.get_model_parallel_group())
            # tpu-lint: disable=TPL001 -- scaler skip after the cross-chip MAX is a host branch; one scalar sync per step by design
            self._scaler._found_inf = bool(found._data[0] > 0)
            self._scaler._unscaled = True
        self._scaler.step(inner)

    def update(self):
        self._scaler.update()

    def minimize(self, optimizer, loss):
        loss.backward()
        self.step(optimizer)
        self.update()

    def __getattr__(self, item):
        return getattr(self._scaler, item)
