"""Fleet facade (reference: `fleet/fleet.py:99` — init, distributed_model,
distributed_optimizer)."""
from __future__ import annotations

from .topology import (CommunicateTopology, HybridCommunicateGroup, ParallelMode,
                       _get_hybrid_group)
from .distributed_strategy import DistributedStrategy
from . import topology as _topo_mod
from ..parallel_env import ParallelEnv, init_parallel_env
from . import recompute as _recompute_mod
from .recompute import recompute, recompute_sequential  # noqa
from .utils import sequence_parallel_utils  # noqa
from .layers import mpu  # noqa
from .meta_parallel import (PipelineLayer, LayerDesc, SharedLayerDesc,  # noqa
                            PipelineParallel, TensorParallel)
from .meta_optimizers import HybridParallelOptimizer, HybridParallelGradScaler  # noqa


class _FleetState:
    def __init__(self):
        self.strategy = None
        self.hcg = None
        self.initialized = False


_state = _FleetState()


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    """fleet.init (reference `fleet/fleet.py:169`): bring up env + hybrid topology."""
    init_parallel_env()
    _state.strategy = strategy or DistributedStrategy()
    hybrid = _state.strategy.hybrid_configs
    env = ParallelEnv()
    dp = hybrid.get("dp_degree", 1)
    mp = hybrid.get("mp_degree", 1)
    pp = hybrid.get("pp_degree", 1)
    sharding = hybrid.get("sharding_degree", 1)
    sep = hybrid.get("sep_degree", 1)
    world = env.world_size
    # auto-fill dp like the reference
    known = mp * pp * sharding * sep
    if dp * known != world and world % known == 0:
        dp = world // known
    topo = CommunicateTopology(["data", "pipe", "sharding", "sep", "model"],
                               [dp, pp, sharding, sep, mp])
    _state.hcg = HybridCommunicateGroup(topo)
    _topo_mod._HYBRID_PARALLEL_GROUP = _state.hcg
    _state.initialized = True
    return None


def is_initialized():
    return _state.initialized


def get_hybrid_communicate_group():
    return _state.hcg


def worker_index():
    return ParallelEnv().rank


def worker_num():
    return ParallelEnv().world_size


def is_first_worker():
    return worker_index() == 0


def barrier_worker():
    from ..communication.group import barrier
    barrier()


def distributed_model(model):
    """Wrap per parallel mode (reference `fleet/model.py`)."""
    from ..parallel import DataParallel
    if _state.hcg is None:
        init()
    hcg = _state.hcg
    mode = hcg.get_parallel_mode()
    if mode == ParallelMode.PIPELINE_PARALLEL:
        return PipelineParallel(model, hcg, _state.strategy)
    if mode == ParallelMode.TENSOR_PARALLEL:
        return TensorParallel(model, hcg, _state.strategy)
    if hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model, group=hcg.get_data_parallel_group())
    return model


def distributed_optimizer(optimizer, strategy=None):
    """Wrap optimizer for hybrid runs (reference `fleet/optimizer.py`)."""
    if _state.hcg is None:
        init(strategy=strategy)
    hcg = _state.hcg
    if hcg.get_mesh().size > 1 or hcg.get_model_parallel_world_size() > 1 \
            or hcg.get_pipe_parallel_world_size() > 1 \
            or hcg.get_sharding_parallel_world_size() > 1:
        return HybridParallelOptimizer(optimizer, hcg, _state.strategy)
    return optimizer


def distributed_scaler(scaler):
    return HybridParallelGradScaler(scaler, _state.hcg)


class UserDefinedRoleMaker:
    def __init__(self, *a, **kw):
        pass


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=False, **kw):
        self.is_collective = is_collective
