"""Elastic training: membership, heartbeat, scale up/down decisions.

Reference parity: `fleet/elastic/manager.py:126` (ElasticManager — etcd-backed
member registry, heartbeat watchdog, np scaling, pod relaunch).

TPU-native: no etcd dependency — process liveness is the heartbeat (the launch
CLI polls its containers) and membership lives in the manager; the decision
logic (restart vs scale-down vs exit, min/max np window, ELASTIC_TIMEOUT
grace) matches the reference.
"""
from __future__ import annotations

import time
from enum import IntEnum
from typing import Dict, Optional


class ElasticStatus(IntEnum):
    COMPLETED = 0
    NORMAL = 1       # all members healthy
    RESTART = 2      # restart the pod at the same size
    HOLD = 3         # members missing but inside the grace window
    EXIT = 4         # below min np / restarts exhausted — give up


def parse_np(np_spec) -> tuple:
    """'2:4' -> (2, 4); '4' / 4 -> (4, 4) (ref manager np parsing)."""
    if np_spec is None:
        return (1, 1)
    if isinstance(np_spec, int):
        return (np_spec, np_spec)
    s = str(np_spec)
    if ":" in s:
        lo, hi = s.split(":")
        lo, hi = int(lo), int(hi)
    else:
        lo = hi = int(s)
    if lo < 1 or hi < lo:
        raise ValueError(f"bad np range {np_spec!r}")
    return lo, hi


class ElasticManager:
    """Tracks member heartbeats and decides pod actions (ref ElasticManager)."""

    def __init__(self, np_spec="1", timeout: float = 10.0, max_restart: int = 3,
                 clock=time.monotonic):
        self.min_np, self.max_np = parse_np(np_spec)
        self.timeout = timeout
        self.max_restart = max_restart
        self.restarts = 0
        self._clock = clock
        self._members: Dict[int, float] = {}
        self._grace_start: Optional[float] = None
        self._reported = False

    # ---- membership ----
    def register(self, rank: int):
        self._members[rank] = self._clock()
        self._grace_start = None

    def heartbeat(self, rank: int):
        if rank in self._members:
            self._members[rank] = self._clock()

    def deregister(self, rank: int):
        self._members.pop(rank, None)

    def report_failure(self, rank: int):
        """Definitive failure (process exit): marks the member dead with no
        grace window (stale heartbeats, by contrast, get ELASTIC_TIMEOUT)."""
        if rank in self._members:
            self._members[rank] = float("-inf")
        self._reported = True

    @property
    def np(self) -> int:
        return len(self._members)

    def live_members(self):
        now = self._clock()
        return [r for r, t in self._members.items()
                if now - t <= self.timeout]

    def dead_members(self):
        now = self._clock()
        return [r for r, t in self._members.items()
                if now - t > self.timeout]

    # ---- decision (ref manager watch loop) ----
    def decide(self, all_done: bool = False) -> ElasticStatus:
        if all_done:
            return ElasticStatus.COMPLETED
        dead = self.dead_members()
        if not dead:
            self._grace_start = None
            return ElasticStatus.NORMAL
        # grace window: transient (stale-heartbeat) failures get
        # ELASTIC_TIMEOUT to come back; reported process exits do not
        if not self._reported:
            now = self._clock()
            if self._grace_start is None:
                self._grace_start = now
            if now - self._grace_start < self.timeout:
                return ElasticStatus.HOLD
        live = len(self.live_members())
        if live >= self.min_np:
            return ElasticStatus.RESTART       # relaunch at the scaled size
        if self.restarts < self.max_restart:
            return ElasticStatus.RESTART
        return ElasticStatus.EXIT

    def scaled_np(self) -> int:
        """Target world size for the next launch: live members clamped to
        [min_np, max_np] (scale down on loss, up to max on recovery)."""
        live = len(self.live_members())
        return max(self.min_np, min(self.max_np, live if live > 0
                                    else self.min_np))

    def on_restart(self):
        self.restarts += 1
        self._members.clear()
        self._grace_start = None
        self._reported = False


from .manager import ELASTIC_AUTO_PARALLEL_EXIT_CODE  # noqa
