"""Elastic constants + helpers (ref fleet/elastic/manager.py)."""
ELASTIC_AUTO_PARALLEL_EXIT_CODE = 101
