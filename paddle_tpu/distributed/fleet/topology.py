"""Hybrid-parallel topology (reference: `fleet/base/topology.py:58` —
`CommunicateTopology`, :144 `HybridCommunicateGroup`).

TPU-native: the topology IS a `jax.sharding.Mesh`.  Axes follow the reference order
["data", "pipe", "sharding", "sep", "model"]; each axis also materializes as a Group for
the eager API, and `get_mesh()` hands the jit path its mesh for GSPMD shardings.
"""
from __future__ import annotations

import collections
import itertools
from functools import reduce

import numpy as np

from ..communication.group import Group, new_group
from ..parallel_env import ParallelEnv

_HYBRID_PARALLEL_GROUP = None


def _get_hybrid_group():
    return _HYBRID_PARALLEL_GROUP


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep", "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections.namedtuple("Coordinate", self._parallel_names)
        self.world_size = int(np.prod(self._dims))
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c) for c in itertools.product(*ranges)]
        self._coord2rank = dict(zip(all_coords, range(len(all_coords))))
        self._rank2coord = dict(zip(self._coord2rank.values(), self._coord2rank.keys()))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def get_rank(self, **args):
        return self._coord2rank[self.coordinate(**args)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        ranks = [self._coord2rank[c] for c in self._coord2rank
                 if c[axis] == index]
        return sorted(ranks)

    def get_comm_list(self, axis_name):
        """All rank-lists that differ only along axis_name."""
        axis = self._parallel_names.index(axis_name)
        other = [n for n in self._parallel_names if n != axis_name]
        ranges = [range(self.get_dim(n)) for n in other]
        comm_list = []
        for combo in itertools.product(*ranges):
            fixed = dict(zip(other, combo))
            ranks = [self.get_rank(**{**fixed, axis_name: i})
                     for i in range(self._dims[axis])]
            comm_list.append(ranks)
        return comm_list

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        tf = coord._replace(**kwargs)._asdict()
        return self.get_rank(**tf)


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        env = ParallelEnv()
        self.global_rank = env.rank
        self._dp_degree = self._topo.get_dim("data")
        self._mp_degree = self._topo.get_dim("model")
        self._pp_degree = self._topo.get_dim("pipe")
        self._sharding_degree = self._topo.get_dim("sharding")
        self._sep_degree = self._topo.get_dim("sep") \
            if "sep" in self._topo.get_hybrid_group_names() else 1

        self._dp_group, self._dp_comm_group = self._set_comm_group("data")
        self._mp_group, self._mp_comm_group = self._set_comm_group("model")
        self._pp_group, self._pp_comm_group = self._set_comm_group("pipe")
        self._sharding_group, self._sharding_comm_group = self._set_comm_group("sharding")
        if self._sep_degree > 1:
            self._sep_group, self._sep_comm_group = self._set_comm_group("sep")
        else:
            self._sep_group, self._sep_comm_group = None, None

        coord = self._topo.get_coord(self.global_rank)
        self.stage_id = coord.pipe
        self._mesh = None

    def _set_comm_group(self, axis_name):
        comm_lists = self._topo.get_comm_list(axis_name)
        my_group = None
        my_ranks = None
        for ranks in comm_lists:
            group = new_group(ranks)
            if self.global_rank in ranks:
                my_group = group
                my_ranks = ranks
        return my_ranks, my_group

    # ---- mesh (the TPU-native artifact) ----
    def get_mesh(self):
        """jax Mesh with axes (dp, pp, sharding[, sep], mp) over all devices.

        Built lazily; in a single process over N local devices this is the N-device
        mesh used by the jitted hybrid train step.
        """
        if self._mesh is None:
            import jax
            from jax.sharding import Mesh
            names = []
            sizes = []
            for name, size in (("dp", self._dp_degree), ("pp", self._pp_degree),
                               ("sharding", self._sharding_degree),
                               ("sep", self._sep_degree), ("mp", self._mp_degree)):
                names.append(name)
                sizes.append(size)
            n = int(np.prod(sizes))
            devs = np.array(jax.devices()[:n]).reshape(sizes)
            self._mesh = Mesh(devs, tuple(names))
        return self._mesh

    # ---- queries (reference API) ----
    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and \
                self._sharding_degree == 1 and self._dp_degree > 1:
            return ParallelMode.DATA_PARALLEL
        if self._sharding_degree > 1 and self._mp_degree == 1 and self._pp_degree == 1:
            return ParallelMode.SHARDING_PARALLEL
        if self._mp_degree > 1 and self._pp_degree == 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        return ParallelMode.DATA_PARALLEL

    def get_data_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).data

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_comm_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group[0]

    def get_model_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).model

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_comm_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group[0]

    def get_stage_id(self):
        return self.stage_id

    def get_pipe_parallel_rank(self):
        return self.stage_id

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_comm_group

    def get_sharding_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).sharding

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_comm_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group[0]

    def get_sep_parallel_rank(self):
        c = self._topo.get_coord(self.global_rank)
        return getattr(c, "sep", 0)

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._sep_comm_group

    def get_p2p_groups(self):
        return None

    def topology(self):
        return self._topo

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage_id, **kwargs)


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
