"""DistributedStrategy (reference: `fleet/base/distributed_strategy.py:121`, proto
`fluid/framework/distributed_strategy.proto`).

Plain-attribute config object covering the reference's strategy surface; consumed by
fleet.init / distributed_model / distributed_optimizer.
"""
from __future__ import annotations


class DistributedStrategy:
    def __init__(self):
        # hybrid parallel dims (reference hybrid_configs)
        self.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                               "sharding_degree": 1, "sep_degree": 1,
                               "mp_configs": {}, "pp_configs": {}}
        # amp
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0, "custom_white_list": [],
                            "custom_black_list": [], "use_pure_fp16": False,
                            "use_fp16_guard": True, "use_bf16": True}
        # recompute
        self.recompute = False
        self.recompute_configs = {"checkpoints": [], "enable_offload": False}
        # sharding (ZeRO)
        self.sharding = False
        self.sharding_configs = {"sharding_degree": 1, "stage": 1, "offload": False,
                                 "accumulate_steps": 1}
        # pipeline
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1,
                                 "schedule_mode": "1F1B"}
        # tensor parallel (static-graph era config, kept for parity)
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        # misc meta-optimizer knobs (accepted; most are no-ops on TPU)
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.fp16_allreduce = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.heter_ccl_mode = False
        self.is_fl_ps_mode = False
        self.a_sync = False
        self.a_sync_configs = {}
        self.auto = False
        self.semi_auto = False
        self.auto_search = False
        self.without_graph_optimization = True

    def to_mesh_config(self):
        """Lower the strategy to the compiled trainer's MeshConfig — the
        TPU-native equivalent of the reference's meta-optimizer pass stack
        consuming this object (each knob selects a program transformation;
        here they select mesh axes / remat / ZeRO stage)."""
        from ...parallel import MeshConfig
        h = self.hybrid_configs
        sharding_degree = 1
        sharding_stage = 1
        if self.sharding:
            sharding_degree = int(self.sharding_configs.get("sharding_degree", 1))
            sharding_stage = int(self.sharding_configs.get("stage", 1))
        elif h.get("sharding_degree", 1) > 1:
            sharding_degree = int(h["sharding_degree"])
        pp = int(h.get("pp_degree", 1))
        micro = int(self.pipeline_configs.get("accumulate_steps", 1)) \
            if (self.pipeline or pp > 1) else 1
        mp = int(h.get("mp_degree", 1))
        if self.tensor_parallel:
            mp = max(mp, int(self.tensor_parallel_configs.get(
                "tensor_parallel_degree", 1)))
        return MeshConfig(
            dp=int(h.get("dp_degree", 1)),
            pp=pp,
            sharding=sharding_degree,
            mp=mp,
            ep=int(h.get("ep_degree", 1)),
            cp=int(h.get("sep_degree", 1)),   # sequence axis -> ring CP
            vpp=int(h.get("pp_configs", {}).get("virtual_pp_degree", 1) or 1),
            sharding_stage=sharding_stage,
            micro_batches=max(micro, 1),
            sequence_parallel=bool(h.get("mp_configs", {})
                                   .get("sequence_parallel", False)),
            remat=bool(self.recompute))

    def __repr__(self):
        keys = ["hybrid_configs", "amp", "recompute", "sharding", "pipeline"]
        return "DistributedStrategy(" + ", ".join(
            f"{k}={getattr(self, k)!r}" for k in keys) + ")"
