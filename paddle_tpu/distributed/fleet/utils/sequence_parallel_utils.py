"""Megatron-style sequence parallelism (reference:
`fleet/utils/sequence_parallel_utils.py` — ScatterOp :83, AllGatherOp :109,
ReduceScatterOp :125, ColumnSequenceParallelLinear :228, RowSequenceParallelLinear
:340, register_sequence_parallel_allreduce_hooks :190).

TPU-native note: under jit/GSPMD, sequence parallelism is a sharding of the sequence
axis (PartitionSpec('mp') on dim 0 outside TP regions) — XLA inserts these exact
all-gather/reduce-scatter pairs.  These eager ops keep the reference's explicit form
for the imperative path and stamp `sequence_parallel` marks used by the fused
allreduce hooks.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....core import autograd as _ag
from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn.initializer import XavierNormal
from ....nn.layer.layers import Layer
from ...communication.ops import ReduceOp, all_gather, all_reduce
from ..layers.mpu import _mp_info


def _make_node(name, x, out_data, vjp_fn):
    out = Tensor(out_data)
    if not x.stop_gradient and _ag.is_grad_enabled():
        node = _ag.GradNode(name, vjp_fn, [x], 1, [(tuple(out_data.shape),
                                                    out_data.dtype)])
        out.stop_gradient = False
        out._grad_node = node
    return out


def scatter(x):
    """Split sequence dim (0) to this mp rank; backward = all-gather (ScatterOp)."""
    world, rank, g = _mp_info()
    if world <= 1:
        return x
    piece = jnp.split(x._data, world, axis=0)[rank]

    def vjp_fn(cot):
        t = Tensor(cot, stop_gradient=True)
        parts = []
        all_gather(parts, t, group=g)
        return (jnp.concatenate([p._data for p in parts], axis=0),)
    return _make_node("sp_scatter", x, piece, vjp_fn)


def all_gather_sp(x):
    """Gather sequence dim; backward = take local slice (AllGatherOp)."""
    world, rank, g = _mp_info()
    if world <= 1:
        return x
    parts = []
    all_gather(parts, x, group=g)
    full = jnp.concatenate([p._data for p in parts], axis=0)

    def vjp_fn(cot):
        return (jnp.split(cot, world, axis=0)[rank],)
    return _make_node("sp_allgather", x, full, vjp_fn)


def reduce_scatter_sp(x):
    """Sum over mp group then keep local sequence slice; backward = all-gather
    (ReduceScatterOp)."""
    world, rank, g = _mp_info()
    if world <= 1:
        return x
    t = Tensor(x._data)
    all_reduce(t, ReduceOp.SUM, group=g)
    piece = jnp.split(t._data, world, axis=0)[rank]

    def vjp_fn(cot):
        tt = Tensor(cot, stop_gradient=True)
        parts = []
        all_gather(parts, tt, group=g)
        return (jnp.concatenate([p._data for p in parts], axis=0),)
    return _make_node("sp_reduce_scatter", x, piece, vjp_fn)


class ScatterOp:
    @staticmethod
    def apply(x):
        return scatter(x)


class AllGatherOp:
    @staticmethod
    def apply(x):
        return all_gather_sp(x)


class ReduceScatterOp:
    @staticmethod
    def apply(x):
        return reduce_scatter_sp(x)


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter):
    return getattr(parameter, "sequence_parallel", False)


def create_fused_allreduce_gradient_hook(parameter_list, accumulation_steps):
    world, _, g = _mp_info()
    step = {"n": 0}

    def hook(grad):
        step["n"] += 1
        if step["n"] % max(accumulation_steps, 1) == 0 and world > 1:
            all_reduce(grad, ReduceOp.SUM, group=g)
        return grad
    return hook


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """(reference :190): norm/bias params marked sequence_parallel get their grads
    allreduced across the mp group (their math ran on a sequence shard)."""
    params = [p for p in model.parameters() if is_sequence_parallel_parameter(p)]
    world, _, g = _mp_info()
    if world <= 1:
        return
    for p in params:
        def hook(grad, _p=p):
            all_reduce(grad, ReduceOp.SUM, group=g)
            return grad
        p.register_hook(hook)


class ColumnSequenceParallelLinear(Layer):
    """(reference :228): all-gather sequence shards -> column-parallel matmul."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None,
                 gather_output=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        world, rank, _ = _mp_info()
        self.world_size = world
        assert out_features % world == 0
        self.weight = self.create_parameter(
            shape=[in_features, out_features // world], attr=weight_attr,
            default_initializer=XavierNormal())
        self.weight.is_distributed = world > 1
        self.weight._dist_axes = (None, "mp")
        self.bias = self.create_parameter(shape=[out_features // world], attr=None,
                                          is_bias=True) if has_bias else None

    def forward(self, x):
        x = all_gather_sp(x)
        return F.linear(x, self.weight, self.bias)


class RowSequenceParallelLinear(Layer):
    """(reference :340): row-parallel matmul -> reduce-scatter over sequence dim."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=True, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        world, rank, _ = _mp_info()
        self.world_size = world
        assert in_features % world == 0
        self.weight = self.create_parameter(
            shape=[in_features // world, out_features], attr=weight_attr,
            default_initializer=XavierNormal())
        self.weight.is_distributed = world > 1
        self.weight._dist_axes = ("mp", None)
        self.bias = self.create_parameter(shape=[out_features], attr=None,
                                          is_bias=True) if has_bias else None
        if self.bias is not None:
            mark_as_sequence_parallel_parameter(self.bias)

    def forward(self, x):
        out = F.linear(x, self.weight, None)
        out = reduce_scatter_sp(out)
        if self.bias is not None:
            out = out + self.bias
        return out


GatherOp = AllGatherOp  # reference alias
