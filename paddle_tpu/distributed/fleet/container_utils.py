def build_desc_layer(desc):
    return desc.build_layer()
