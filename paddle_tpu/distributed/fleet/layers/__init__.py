from . import mpu  # noqa
