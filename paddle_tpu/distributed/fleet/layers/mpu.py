"""Tensor/model-parallel layers (reference: `fleet/layers/mpu/mp_layers.py` —
`VocabParallelEmbedding` :35, `ColumnParallelLinear` :173, `RowParallelLinear` :343,
`ParallelCrossEntropy` :524; comm prims `mp_ops.py`).

TPU-native: each layer holds its LOCAL weight shard (reference semantics) and also
stamps `param._dist_axes` with the mesh PartitionSpec so the jit path can hand XLA the
global view (GSPMD inserts the same collectives the reference codes by hand).  The
eager collectives route through communication.ops, identity at world 1.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....core import autograd as _ag
from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn.initializer import Constant, XavierNormal
from ....nn.layer.layers import Layer
from ...communication.ops import ReduceOp, all_gather, all_reduce
from ..topology import _get_hybrid_group


def _mp_info():
    hcg = _get_hybrid_group()
    if hcg is None:
        return 1, 0, None
    return (hcg.get_model_parallel_world_size(), hcg.get_model_parallel_rank(),
            hcg.get_model_parallel_group())


# ---- mp_ops (reference fleet/layers/mpu/mp_ops.py) ----

def _c_identity(x, group=None):
    """Forward identity, backward allreduce (reference `_c_identity`)."""
    world, _, g = _mp_info()
    if world <= 1:
        return x

    def vjp_fn(cot):
        t = Tensor(cot, stop_gradient=True)
        all_reduce(t, ReduceOp.SUM, group=g)
        return (t._data,)
    node = _ag.GradNode("c_identity", vjp_fn, [x], 1,
                        [(tuple(x._data.shape), x._data.dtype)])
    out = Tensor(x._data)
    if not x.stop_gradient and _ag.is_grad_enabled():
        out.stop_gradient = False
        out._grad_node = node
    return out


def _mp_allreduce(x, group=None):
    """Forward allreduce, backward identity (reference `_mp_allreduce`)."""
    world, _, g = _mp_info()
    if world <= 1:
        return x
    t = Tensor(x._data)
    all_reduce(t, ReduceOp.SUM, group=g)

    def vjp_fn(cot):
        return (cot,)
    node = _ag.GradNode("mp_allreduce_sum", vjp_fn, [x], 1,
                        [(tuple(t._data.shape), t._data.dtype)])
    if not x.stop_gradient and _ag.is_grad_enabled():
        t.stop_gradient = False
        t._grad_node = node
    return t


def _c_concat(x, group=None):
    """Gather along last dim across mp ranks (reference `_c_concat`)."""
    world, rank, g = _mp_info()
    if world <= 1:
        return x
    parts = []
    all_gather(parts, x, group=g)
    out_data = jnp.concatenate([p._data for p in parts], axis=-1)

    def vjp_fn(cot):
        piece = jnp.split(cot, world, axis=-1)[rank]
        return (piece,)
    node = _ag.GradNode("c_concat", vjp_fn, [x], 1, [(tuple(out_data.shape),
                                                      out_data.dtype)])
    out = Tensor(out_data)
    if not x.stop_gradient and _ag.is_grad_enabled():
        out.stop_gradient = False
        out._grad_node = node
    return out


def _c_split(x, group=None):
    """Keep this rank's slice of the last dim (reference `_c_split`)."""
    world, rank, g = _mp_info()
    if world <= 1:
        return x
    from ....ops.manipulation import split
    return split(x, world, axis=-1)[rank]


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None,
                 name=None):
        super().__init__()
        world, rank, _ = _mp_info()
        self.world_size = world
        self.rank = rank
        self.origin_num_embeddings = num_embeddings
        assert num_embeddings % world == 0
        per = num_embeddings // world
        self.vocab_start_index = rank * per
        self._per_part_size = per
        self.weight = self.create_parameter(
            shape=[per, embedding_dim], attr=weight_attr,
            default_initializer=XavierNormal())
        self.weight.is_distributed = world > 1
        self.weight._dist_axes = ("mp", None)  # vocab dim sharded over mp

    def forward(self, x):
        if self.world_size <= 1:
            return F.embedding(x, self.weight)
        # mask out-of-shard ids, embed, allreduce partial sums
        from ....core.tensor import apply
        start = self.vocab_start_index
        per = self._per_part_size

        def f(ids, w):
            local = ids - start
            in_range = (local >= 0) & (local < per)
            safe = jnp.where(in_range, local, 0)
            emb = jnp.take(w, safe.astype(jnp.int32), axis=0)
            return jnp.where(in_range[..., None], emb, 0.0)
        out = apply("vocab_parallel_embedding", f, x, self.weight)
        return _mp_allreduce(out)


class ColumnParallelLinear(Layer):
    """W [in, out/world]; forward identity-in, optional gather-out."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        world, rank, _ = _mp_info()
        self.world_size = world
        assert out_features % world == 0
        self.out_per_part = out_features // world
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, self.out_per_part], attr=weight_attr,
            default_initializer=XavierNormal())
        self.weight.is_distributed = world > 1
        self.weight._dist_axes = (None, "mp")
        if has_bias:
            self.bias = self.create_parameter(
                shape=[self.out_per_part], attr=None, is_bias=True)
            self.bias.is_distributed = world > 1
            self.bias._dist_axes = ("mp",)
        else:
            self.bias = None

    def forward(self, x):
        x = _c_identity(x)
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = _c_concat(out)
        return out


class RowParallelLinear(Layer):
    """W [in/world, out]; input either already split or split here; allreduce out."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        world, rank, _ = _mp_info()
        self.world_size = world
        assert in_features % world == 0
        self.in_per_part = in_features // world
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[self.in_per_part, out_features], attr=weight_attr,
            default_initializer=XavierNormal())
        self.weight.is_distributed = world > 1
        self.weight._dist_axes = ("mp", None)
        if has_bias:
            self.bias = self.create_parameter(shape=[out_features], attr=None,
                                              is_bias=True)
            self.bias._dist_axes = (None,)
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = _c_split(x)
        out = F.linear(x, self.weight, None)
        out = _mp_allreduce(out)
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """TP-parallel softmax CE over the vocab-sharded logits (reference
    `c_softmax_with_cross_entropy`)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        world, rank, g = _mp_info()
        if world <= 1:
            return F.cross_entropy(input, label, reduction="none",
                                   ignore_index=self.ignore_index)
        # logits sharded on last dim: compute global max/sumexp via allreduce
        from ....core.tensor import apply
        per = input.shape[-1]
        start = rank * per

        local_max = Tensor(jnp.max(input._data, axis=-1))
        all_reduce(local_max, ReduceOp.MAX, group=g)
        gmax = local_max._data[..., None]
        sumexp = Tensor(jnp.sum(jnp.exp(input._data.astype(jnp.float32) - gmax), -1))
        all_reduce(sumexp, ReduceOp.SUM, group=g)
        lab = label._data.astype(jnp.int32)
        squeeze = lab.ndim == input._data.ndim and lab.shape[-1] == 1
        if squeeze:
            lab = lab[..., 0]
        local = lab - start
        in_range = (local >= 0) & (local < per)
        safe = jnp.where(in_range, local, 0)
        picked = jnp.take_along_axis(input._data.astype(jnp.float32),
                                     safe[..., None], axis=-1)[..., 0]
        picked = jnp.where(in_range, picked, 0.0)
        picked_t = Tensor(picked)
        all_reduce(picked_t, ReduceOp.SUM, group=g)
        loss = jnp.log(sumexp._data) + gmax[..., 0] - picked_t._data
        return Tensor(loss[..., None] if squeeze else loss)


# mp_ops public names (reference mp_ops.py)
mp_ops = type("mp_ops", (), {"_c_identity": staticmethod(_c_identity),
                             "_c_concat": staticmethod(_c_concat),
                             "_c_split": staticmethod(_c_split),
                             "_mp_allreduce": staticmethod(_mp_allreduce)})
