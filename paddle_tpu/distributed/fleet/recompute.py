"""Recompute / activation checkpointing (reference: `fleet/recompute/recompute.py` —
PyLayer with RNG state replay).

TPU-native: inside jit/`to_static`, `jax.checkpoint` is the engine (XLA remat).  In
eager, a PyLayer-style whole-segment GradNode recomputes the forward under the saved
RNG state at backward time — same semantics, tape-level.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import autograd as _ag
from ...core import generator as _gen
from ...core.tensor import Tensor


def _collect_params(function, tensor_args):
    """Trainable Tensors reachable from `function` itself: a Layer's parameters(), a
    bound method's owner, or Tensors/Layers captured in a plain function's closure.
    These are vjp primals alongside the explicit tensor args — otherwise activation
    checkpointing silently stops training the wrapped layers."""
    seen = {id(t) for t in tensor_args}
    found = []

    def add(t):
        if isinstance(t, Tensor) and id(t) not in seen:
            seen.add(id(t))
            found.append(t)

    def scan(obj, depth=0):
        if isinstance(obj, Tensor):
            add(obj)
            return
        params = getattr(obj, "parameters", None)
        if callable(params):
            try:
                for p in params():
                    add(p)
                return
            except TypeError:
                pass
        # containers of Layers/Tensors (e.g. recompute_sequential closes over a
        # plain list of layers); bounded depth so arbitrary objects can't recurse
        if depth < 3:
            if isinstance(obj, (list, tuple, set)):
                for v in obj:
                    scan(v, depth + 1)
            elif isinstance(obj, dict):
                for v in obj.values():
                    scan(v, depth + 1)

    scan(function)
    owner = getattr(function, "__self__", None)
    if owner is not None:
        scan(owner)
    for cell in getattr(function, "__closure__", None) or ():
        try:
            scan(cell.cell_contents)
        except ValueError:
            continue
    return [p for p in found
            if not p.stop_gradient and jnp.issubdtype(p._data.dtype, jnp.inexact)]


def recompute(function, *args, **kwargs):
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)

    tensor_args = [a for a in args if isinstance(a, Tensor)]
    params = _collect_params(function, tensor_args)
    need_grad = _ag.is_grad_enabled() and any(
        not t.stop_gradient for t in tensor_args + params)

    rng_key = _gen.default_generator().get_state() if preserve_rng_state else None

    with _ag.set_grad_enabled(False):
        if preserve_rng_state:
            saved = _gen.default_generator().get_state()
            _gen.default_generator().set_state(rng_key)
        out = function(*args, **kwargs)
        if preserve_rng_state:
            _gen.default_generator().set_state(saved)
    if not need_grad:
        return out

    single = not isinstance(out, (tuple, list))
    out_list = [out] if single else list(out)

    def vjp_fn(cots):
        if not isinstance(cots, tuple):
            cots = (cots,)
        # rerun forward under grad with the saved RNG state, then pull back
        if preserve_rng_state:
            saved2 = _gen.default_generator().get_state()
            _gen.default_generator().set_state(rng_key)
        datas = [t._data for t in tensor_args] + [p._data for p in params]

        def pure(*ds):
            new_args = []
            it = iter(ds)
            for a in args:
                if isinstance(a, Tensor):
                    new_args.append(Tensor(next(it), stop_gradient=a.stop_gradient))
                else:
                    new_args.append(a)
            # params live inside `function`; substitute their data so jax.vjp sees
            # them as primals, restoring the originals after the re-trace
            originals = [p._data for p in params]
            try:
                for p in params:
                    p._data = next(it)
                with _ag.set_grad_enabled(False):
                    if preserve_rng_state:
                        _gen.default_generator().set_state(rng_key)
                    o = function(*new_args, **kwargs)
                o_list = [o] if not isinstance(o, (tuple, list)) else list(o)
                return tuple(t._data for t in o_list if isinstance(t, Tensor))
            finally:
                for p, od in zip(params, originals):
                    p._data = od

        _, pull = jax.vjp(pure, *datas)
        grads = pull(tuple(cots))
        if preserve_rng_state:
            _gen.default_generator().set_state(saved2)
        res = []
        gi = iter(grads)
        for a in args:
            res.append(next(gi) if isinstance(a, Tensor) else None)
        for _p in params:
            res.append(next(gi))
        return tuple(res)

    specs = [(tuple(t._data.shape), t._data.dtype) for t in out_list
             if isinstance(t, Tensor)]
    # params are node inputs so the engine routes their cotangents to leaf .grad
    node = _ag.GradNode("recompute", vjp_fn, list(args) + params,
                        len([t for t in out_list if isinstance(t, Tensor)]), specs)
    idx = 0
    for t in out_list:
        if isinstance(t, Tensor) and jnp.issubdtype(t._data.dtype, jnp.inexact):
            t.stop_gradient = False
            t._grad_node = node
            t._out_index = idx
        if isinstance(t, Tensor):
            idx += 1
    return out


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Segment a Sequential into recompute chunks (reference recompute_sequential)."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    seg_size = max(len(layers) // segments, 1)
    out = args[0] if len(args) == 1 else args

    def run_segment(start, end, x):
        # bind ONLY this segment's layers into the closure — _collect_params scans
        # closure cells, and closing over the full list would drag every layer's
        # params into every segment's vjp
        seg_layers = layers[start:end]

        def seg_fn(inp):
            h = inp
            for l in seg_layers:
                h = l(h)
            return h
        return recompute(seg_fn, x)

    for s in range(0, len(layers), seg_size):
        out = run_segment(s, min(s + seg_size, len(layers)), out)
    return out
