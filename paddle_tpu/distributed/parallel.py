"""DataParallel (reference: `python/paddle/distributed/parallel.py:191`).

Reference design: EagerReducer buckets grads + overlapped NCCL allreduce
(`reducer.cc:740`).  TPU-native: with a single process per host driving an XLA mesh,
the preferred DP is sharded-jit (see fleet.distributed_model's jit path) where XLA
fuses the gradient reduction into the backward.  This eager wrapper keeps reference
semantics: param broadcast at construction, grad allreduce hooks on backward
(bucketed), `no_sync`, find_unused_parameters accepted.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import parallel_env
from .communication.ops import ReduceOp, all_reduce, broadcast


def sync_params_buffers(model, comm_group=None, src_rank=0, is_model_parallel=False):
    for p in model.parameters():
        broadcast(p, src_rank, group=comm_group)
    for b in model.buffers():
        if b is not None:
            broadcast(b, src_rank, group=comm_group)


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        self.comm_buffer_size = comm_buffer_size
        self._grads_synced = True
        self._enable_sync = True
        env = parallel_env.ParallelEnv()
        self._world = env.world_size if group is None else group.nranks
        if self._world > 1:
            sync_params_buffers(layers, group)
        self._register_hooks()

    def _register_hooks(self):
        """Bucketed gradient fusion (ref EagerReducer, reducer.cc:740):
        grads join a bucket as their hooks fire (reverse autograd order); when
        a bucket fills (comm_buffer_size MB) or the last grad arrives, ONE
        fused flat allreduce runs and the averaged slices are scattered back."""
        if self._world <= 1:
            return
        import weakref
        world = self._world
        group = self.group
        dp_ref = weakref.ref(self)
        params = [p for p in self._layers.parameters() if not p.stop_gradient]
        self._bucket = []           # [(param, local partial-grad data)]
        self._bucket_bytes = 0
        cap = int(self.comm_buffer_size * (1 << 20))

        def flush(current_param=None):
            """Fused allreduce of the bucket.  Every entry is a PARTIAL local
            cotangent (shared params fire once per consumer edge; averaging is
            linear so per-partial averages sum correctly).  Entries other than
            the currently-firing param already had their local partial
            accumulated into .grad by the engine, so they are corrected with
            += (avg - local) — which also preserves grads accumulated under
            no_sync.  The current param's averaged partial is returned for the
            engine's own accumulation.

            Resolves the wrapper through the weakref so nothing reachable from
            the global callback registry or the param hooks strongly holds the
            wrapper — a dropped DataParallel frees by refcount alone."""
            dp = dp_ref()
            if dp is None or not dp._bucket:
                return None
            entries = dp._bucket
            dp._bucket = []
            dp._bucket_bytes = 0
            flat = jnp.concatenate([jnp.ravel(g) for _, g in entries])
            ft = Tensor(flat)
            all_reduce(ft, ReduceOp.SUM, group=group)
            ret = None
            off = 0
            for _p, g in entries:
                n = int(np.prod(g.shape))
                avg = (ft._data[off:off + n] / world).reshape(g.shape)
                off += n
                if _p is current_param:
                    ret = Tensor(avg, stop_gradient=True)
                elif _p.grad is not None:
                    _p.grad._data = _p.grad._data + (avg - g)
                else:  # engine write raced? fall back to the averaged value
                    gt = Tensor(avg, stop_gradient=True)
                    gt.persistable = True
                    _p.grad = gt
            return ret

        self._flush_bucket = flush
        # the remainder bucket flushes when the ENGINE reports the backward
        # finished — hook-fire counting cannot detect completion (shared
        # params fire per consumer edge, unused params never fire).  The global
        # callback holds only a weakref so a dead wrapper auto-deregisters
        # instead of leaking the model and flushing stale buckets forever.
        from ..core import autograd as _ag

        def _post_backward_flush():
            live = dp_ref()
            if live is None:
                _ag.unregister_post_backward_callback(_post_backward_flush)
                return
            live._flush_bucket(None)

        _ag.register_post_backward_callback(_post_backward_flush)
        self._post_backward_cb = _post_backward_flush

        for p in params:
            def hook(grad, _p=p):
                live = dp_ref()
                if live is None or not live._enable_sync:
                    return grad
                live._bucket.append((_p, grad._data))
                live._bucket_bytes += grad._data.size * grad._data.dtype.itemsize
                if live._bucket_bytes >= cap:
                    return live._flush_bucket(_p)
                return grad
            p.register_hook(hook)

    def __del__(self):
        cb = getattr(self, "_post_backward_cb", None)
        if cb is not None:
            from ..core import autograd as _ag
            _ag.unregister_post_backward_callback(cb)

    @contextlib.contextmanager
    def no_sync(self):
        self._enable_sync = False
        try:
            yield
        finally:
            self._enable_sync = True

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def scale_loss(self, loss):
        return loss


init_parallel_env = parallel_env.init_parallel_env
ParallelEnv = parallel_env.ParallelEnv
