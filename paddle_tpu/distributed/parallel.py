"""DataParallel (reference: `python/paddle/distributed/parallel.py:191`).

Reference design: EagerReducer buckets grads + overlapped NCCL allreduce
(`reducer.cc:740`).  TPU-native: with a single process per host driving an XLA mesh,
the preferred DP is sharded-jit (see fleet.distributed_model's jit path) where XLA
fuses the gradient reduction into the backward.  This eager wrapper keeps reference
semantics: param broadcast at construction, grad allreduce hooks on backward
(bucketed), `no_sync`, find_unused_parameters accepted.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import parallel_env
from .communication.ops import ReduceOp, all_reduce, broadcast


def sync_params_buffers(model, comm_group=None, src_rank=0, is_model_parallel=False):
    for p in model.parameters():
        broadcast(p, src_rank, group=comm_group)
    for b in model.buffers():
        if b is not None:
            broadcast(b, src_rank, group=comm_group)


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        self.comm_buffer_size = comm_buffer_size
        self._grads_synced = True
        self._enable_sync = True
        env = parallel_env.ParallelEnv()
        self._world = env.world_size if group is None else group.nranks
        if self._world > 1:
            sync_params_buffers(layers, group)
        self._register_hooks()

    def _register_hooks(self):
        if self._world <= 1:
            return
        world = self._world
        group = self.group
        dp = self

        for p in self._layers.parameters():
            if p.stop_gradient:
                continue

            def hook(grad, _p=p):
                if not dp._enable_sync:
                    return grad
                all_reduce(grad, ReduceOp.SUM, group=group)
                return Tensor(grad._data / world, stop_gradient=True)
            p.register_hook(hook)

    @contextlib.contextmanager
    def no_sync(self):
        self._enable_sync = False
        try:
            yield
        finally:
            self._enable_sync = True

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def scale_loss(self, loss):
        return loss


init_parallel_env = parallel_env.init_parallel_env
ParallelEnv = parallel_env.ParallelEnv
