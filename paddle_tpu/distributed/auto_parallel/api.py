"""Semi-auto SPMD API (reference: `python/paddle/distributed/auto_parallel/` —
shard_tensor interface, dist_attr; `Engine` lives in `engine.py`).

TPU-native: `shard_tensor(x, mesh, placements)` device_puts the array with a
NamedSharding — from then on every jitted computation over it is partitioned by GSPMD,
which performs the reference's completion (sharding propagation), partitioning (SPMD
split), and resharding (collective insertion) inside XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from .process_mesh import ProcessMesh


class Placement:
    pass


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Partial(Placement):
    def __repr__(self):
        return "Partial()"


def _to_partition_spec(placements, mesh: ProcessMesh, ndim):
    from jax.sharding import PartitionSpec as P
    spec = [None] * ndim
    for axis_idx, pl in enumerate(placements):
        if isinstance(pl, Shard):
            spec[pl.dim] = mesh.dim_names[axis_idx]
    return P(*spec)


def shard_tensor(x, mesh: ProcessMesh, placements, dtype=None, place=None,
                 stop_gradient=None):
    """Place a tensor onto the mesh with the given placements."""
    from jax.sharding import NamedSharding
    t = x if isinstance(x, Tensor) else Tensor(x)
    jmesh = mesh.jax_mesh()
    spec = _to_partition_spec(placements, mesh, t._data.ndim)
    sharded = jax.device_put(t._data, NamedSharding(jmesh, spec))
    out = Tensor(sharded, stop_gradient=t.stop_gradient
                 if stop_gradient is None else stop_gradient)
    out._dist_mesh = mesh
    out._dist_placements = placements
    return out


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x, mesh, placements):
    return shard_tensor(x, mesh, placements)


def shard_op(op, mesh=None, in_placements=None, out_placements=None):
    """Annotate an op call with shardings via with_sharding_constraint."""
    def wrapper(*args, **kwargs):
        out = op(*args, **kwargs)
        if mesh is not None and out_placements is not None and isinstance(out, Tensor):
            from jax.sharding import NamedSharding
            spec = _to_partition_spec(out_placements, mesh, out._data.ndim)
            out._data = jax.lax.with_sharding_constraint(
                out._data, NamedSharding(mesh.jax_mesh(), spec))
        return out
    return wrapper
