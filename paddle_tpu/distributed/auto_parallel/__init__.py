from . import api  # noqa
from .api import dtensor_from_fn, reshard, shard_op, shard_tensor  # noqa
from .process_mesh import ProcessMesh  # noqa
