from . import api  # noqa
from .api import dtensor_from_fn, reshard, shard_op, shard_tensor  # noqa
from .engine import Engine  # noqa
from .process_mesh import ProcessMesh  # noqa
