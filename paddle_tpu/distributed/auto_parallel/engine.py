"""Auto-parallel Engine: fit/evaluate/predict over the compiled hybrid trainer.

Reference parity: `python/paddle/distributed/auto_parallel/static/engine.py:55`
(Engine builds a distributed program per mode and drives it).  TPU-native: the
"distributed program" is the HybridParallelTrainer's single jitted step over a
GSPMD mesh; Engine adds the mode loop, metric/log plumbing, and checkpointing
with cross-mesh resharding (ref dist_saver.py + converter.py).
"""
from __future__ import annotations

import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


class Engine:
    """fit/evaluate/predict driver over a model + mesh strategy.

    Either pass a ready `HybridParallelTrainer`, or (config, mesh_config)
    to build one (the flagship GPT family).
    """

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None, config=None, mesh_config=None,
                 devices=None, **trainer_kwargs):
        from ...parallel import HybridParallelTrainer, MeshConfig
        if strategy is not None and mesh_config is None and \
                hasattr(strategy, "to_mesh_config"):
            mesh_config = strategy.to_mesh_config()  # DistributedStrategy knobs
        if model is not None and hasattr(model, "train_step"):
            self.trainer = model
        else:
            assert config is not None, \
                "Engine needs a HybridParallelTrainer or a model config"
            self.trainer = HybridParallelTrainer(
                config, mesh_config or MeshConfig(), devices=devices,
                **trainer_kwargs)
        self._history = {"loss": []}
        self._predict_fn = None

    # ---- data plumbing ----
    @staticmethod
    def _batches(data, batch_size):
        if isinstance(data, (tuple, list)) and len(data) == 2 \
                and hasattr(data[0], "shape"):  # (tokens, labels) array pair
            tokens, labels = np.asarray(data[0]), np.asarray(data[1])
            n = tokens.shape[0]
            bs = batch_size or n
            for i in range(0, n - bs + 1, bs):
                yield tokens[i:i + bs], labels[i:i + bs]
        else:  # iterable of (tokens, labels)
            for batch in data:
                yield np.asarray(batch[0]), np.asarray(batch[1])

    # ---- modes (ref engine.fit :454, evaluate :614, predict :701) ----
    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None,
            log_freq=10, verbose=1, valid_data=None, **kwargs):
        for epoch in range(epochs):
            t0 = time.time()
            for step, (tok, lab) in enumerate(self._batches(train_data,
                                                            batch_size)):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                loss = float(self.trainer.train_step(tok, lab))
                self._history["loss"].append(loss)
                if verbose and step % log_freq == 0:
                    print(f"[engine] epoch {epoch} step {step} "
                          f"loss {loss:.4f}", flush=True)
            if valid_data is not None and verbose:
                vl = self.evaluate(valid_data, batch_size, verbose=0)
                print(f"[engine] epoch {epoch} val_loss {vl:.4f} "
                      f"({time.time() - t0:.1f}s)", flush=True)
        return self._history

    def evaluate(self, eval_data, batch_size=None, steps=None, verbose=1,
                 **kwargs):
        losses = []
        for step, (tok, lab) in enumerate(self._batches(eval_data, batch_size)):
            if steps is not None and step >= steps:
                break
            losses.append(float(self.trainer.eval_loss(tok, lab)))
        mean = float(np.mean(losses)) if losses else float("nan")
        if verbose:
            print(f"[engine] eval_loss {mean:.4f}", flush=True)
        return mean

    def predict(self, test_data, batch_size=None, steps=None, verbose=0,
                **kwargs):
        from ...models import gpt as gpt_mod
        tr = self.trainer
        if self._predict_fn is None:
            cfg = tr.config
            self._predict_fn = jax.jit(
                lambda p, t: gpt_mod.forward(p, t, cfg))
        outs = []
        data = test_data if isinstance(test_data, (tuple, list)) \
            else (test_data,)
        tokens = np.asarray(data[0])
        n = tokens.shape[0]
        bs = batch_size or n
        for i in range(0, n, bs):   # includes the tail remainder batch
            logits = self._predict_fn(tr.params,
                                      jnp.asarray(tokens[i:min(i + bs, n)]))
            outs.append(np.asarray(logits))
        return np.concatenate(outs, axis=0) if outs else None

    # ---- checkpoint with cross-mesh resharding ----
    def save(self, path, training=True):
        from .. import checkpoint as ckpt
        state = {"params": self.trainer.params}
        if training:
            state["opt"] = self.trainer.opt_state
        ckpt.save_state_dict(state, path)

    def load(self, path, strict=True, load_optimizer=True):
        """Reload onto THIS engine's mesh — which may differ from the mesh the
        checkpoint was saved on (ref converter.py cross-mesh resume)."""
        from .. import checkpoint as ckpt
        tr = self.trainer
        # load to host first: the checkpoint may or may not contain optimizer
        # state, so resharding is applied per present section
        state = ckpt.load_state_dict(path)
        tr.params = jax.tree_util.tree_map(
            lambda a, sh: jax.device_put(a, sh), state["params"],
            tr.param_shardings)
        if load_optimizer and "opt" in state:
            opt = state["opt"]
            m = jax.tree_util.tree_map(lambda a, sh: jax.device_put(a, sh),
                                       opt["m"], tr._m_shardings)
            v = jax.tree_util.tree_map(lambda a, sh: jax.device_put(a, sh),
                                       opt["v"], tr._m_shardings)
            tr.opt_state = {"m": m, "v": v, "step": jnp.asarray(opt["step"])}
        return self

    @property
    def history(self):
        return self._history
