"""ProcessMesh (reference: `phi/core/distributed/auto_parallel/process_mesh.h`,
`python/paddle/distributed/auto_parallel/process_mesh.py`).

TPU-native: a ProcessMesh IS a `jax.sharding.Mesh` — `jax_mesh()` returns it; shard
specs map to PartitionSpecs and GSPMD does completion/partitioning (the reference's
Completer/Partitioner/Resharder pipeline collapses into XLA sharding propagation).
"""
from __future__ import annotations

import numpy as np


class ProcessMesh:
    def __init__(self, mesh, dim_names=None, process_ids=None):
        arr = np.asarray(mesh)
        self._shape = list(arr.shape)
        self._process_ids = arr.flatten().tolist()
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    @property
    def shape(self):
        return self._shape

    @property
    def process_ids(self):
        return self._process_ids

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def ndim(self):
        return len(self._shape)

    def get_dim_size(self, dim_name):
        return self._shape[self._dim_names.index(dim_name)]

    def jax_mesh(self):
        if self._jax_mesh is None:
            import jax
            from jax.sharding import Mesh
            devs = np.asarray(jax.devices())[np.asarray(self._process_ids)] \
                .reshape(self._shape)
            self._jax_mesh = Mesh(devs, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and self._shape == other._shape
                and self._process_ids == other._process_ids)

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dim_names={self._dim_names})"
