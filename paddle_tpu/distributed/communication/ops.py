"""Eager collective API (reference: `python/paddle/distributed/communication/` — 14
modules; C++ `ProcessGroup` `fluid/distributed/collective/process_group.h:53`).

TPU-native: a collective over a Group executes as a jitted XLA collective over a 1-D
device mesh spanning the group's ranks (one device per rank, ICI/DCN routed by XLA) —
the ProcessGroupNCCL/comm-stream machinery has no analog because the XLA runtime owns
scheduling.  With world_size==1 every collective degrades to its identity semantics,
matching the reference.  In-jit code should prefer mesh-sharded programs (GSPMD) over
these eager calls; this API exists for the imperative surface (DataParallel hooks,
barriers, object exchange).
"""
from __future__ import annotations

import pickle
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from .group import Group, _get_global_group


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class _Task:
    """Completed-task handle (ProcessGroup Task parity; XLA dispatch is async under
    the hood, completion happens on first use of the result)."""

    def __init__(self, tensors=None):
        self._tensors = tensors or []

    def wait(self):
        for t in self._tensors:
            if isinstance(t, Tensor):
                jax.block_until_ready(t._data)
        return True

    def is_completed(self):
        return True


def _group(group) -> Group:
    return group if group is not None else _get_global_group()


def _multiproc() -> bool:
    return jax.process_count() > 1


def _group_mesh(group: Group):
    """1-D mesh with one device per group rank (first addressable device of each
    process)."""
    per_proc = {}
    for d in jax.devices():
        per_proc.setdefault(d.process_index, d)
    devs = [per_proc[r] for r in group.ranks]
    from jax.sharding import Mesh
    return Mesh(np.array(devs), ("x",))


def _to_global(x_data, group: Group):
    """Assemble a [nranks, ...] global array from each process's local contribution."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _group_mesh(group)
    sharding = NamedSharding(mesh, P("x"))
    local_dev = jax.local_devices()[0]
    local = jax.device_put(jnp.asarray(x_data)[None], local_dev)
    shape = (group.nranks,) + tuple(x_data.shape)
    return jax.make_array_from_single_device_arrays(shape, sharding, [local]), mesh


_collective_jit_cache = {}


def _replicated_jit(key, fn, mesh):
    """Cached jit of a collective body over `mesh` with a replicated output
    every process can read locally.  Caching on (key, mesh) keeps eager
    collectives (e.g. DataParallel's per-param allreduce hooks) from re-tracing
    a fresh lambda on every call."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    k = (key, mesh)
    got = _collective_jit_cache.get(k)
    if got is None:
        got = jax.jit(fn, out_shardings=NamedSharding(mesh, P()))
        _collective_jit_cache[k] = got
    return got


def _from_global(garr):
    shards = [s for s in garr.addressable_shards]
    return shards[0].data[0]


def _reduce_fn(op):
    return {ReduceOp.SUM: jnp.sum, ReduceOp.MAX: jnp.max, ReduceOp.MIN: jnp.min,
            ReduceOp.PROD: jnp.prod, ReduceOp.AVG: jnp.mean}[op]


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    g = _group(group)
    if g.nranks <= 1:
        return _Task([tensor])
    if not _multiproc():
        raise RuntimeError(
            "eager all_reduce across ranks needs jax.distributed (launch via "
            "paddle_tpu.distributed.launch); inside jit use mesh sharding instead")
    garr, mesh = _to_global(tensor._data, g)
    red = _reduce_fn(op)
    fn = _replicated_jit(("reduce", op), lambda a: red(a, axis=0), mesh)
    tensor._data = jnp.asarray(np.asarray(fn(garr)))
    return _Task([tensor])


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    g = _group(group)
    if g.nranks <= 1:
        tensor_list.append(Tensor(tensor._data))
        return _Task(tensor_list)
    if not _multiproc():
        raise RuntimeError("eager all_gather needs jax.distributed")
    garr, mesh = _to_global(tensor._data, g)
    full = np.asarray(_replicated_jit("gather", lambda a: a, mesh)(garr))
    for i in range(g.nranks):
        tensor_list.append(Tensor(jnp.asarray(full[i])))
    return _Task(tensor_list)


def all_gather_object(object_list, obj, group=None):
    g = _group(group)
    if g.nranks <= 1:
        object_list.append(obj)
        return
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    sizes = []
    size_t = Tensor(jnp.asarray([payload.size], jnp.int64))
    size_list: List[Tensor] = []
    all_gather(size_list, size_t, group)
    maxlen = int(max(int(s._data[0]) for s in size_list))
    padded = np.zeros(maxlen, np.uint8)
    padded[:payload.size] = payload
    data_list: List[Tensor] = []
    all_gather(data_list, Tensor(jnp.asarray(padded)), group)
    for s, d in zip(size_list, data_list):
        n = int(s._data[0])
        object_list.append(pickle.loads(bytes(np.asarray(d._data)[:n])))


def broadcast(tensor, src, group=None, sync_op=True):
    g = _group(group)
    if g.nranks <= 1:
        return _Task([tensor])
    if not _multiproc():
        raise RuntimeError("eager broadcast needs jax.distributed")
    src_in_group = g.get_group_rank(src) if src in g.ranks else src
    gathered: List[Tensor] = []
    all_gather(gathered, tensor, group)
    tensor._data = gathered[src_in_group]._data
    return _Task([tensor])


def broadcast_object_list(object_list, src, group=None):
    g = _group(group)
    if g.nranks <= 1:
        return
    gathered: List = []
    all_gather_object(gathered, object_list, group)
    src_in_group = g.get_group_rank(src) if src in g.ranks else src
    object_list[:] = gathered[src_in_group]


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    g = _group(group)
    if g.nranks <= 1:
        return _Task([tensor])
    all_reduce(tensor, op, group)
    # non-dst ranks keep the reduced value too (superset of reference semantics)
    return _Task([tensor])


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    g = _group(group)
    if g.nranks <= 1:
        src = tensor_list[0] if isinstance(tensor_list, (list, tuple)) else tensor_list
        tensor._data = src._data
        return _Task([tensor])
    stacked = Tensor(jnp.stack([t._data for t in tensor_list]))
    all_reduce(stacked, op, group)
    tensor._data = stacked._data[g.rank]
    return _Task([tensor])


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = _group(group)
    if g.nranks <= 1:
        if tensor_list:
            tensor._data = tensor_list[0]._data
        return _Task([tensor])
    gathered: List[Tensor] = []
    payload = Tensor(jnp.stack([t._data for t in tensor_list])) if tensor_list \
        else Tensor(jnp.zeros((g.nranks,) + tuple(tensor._data.shape), tensor._data.dtype))
    all_gather(gathered, payload, group)
    src_in_group = g.get_group_rank(src) if src in g.ranks else src
    tensor._data = gathered[src_in_group]._data[g.rank]
    return _Task([tensor])


def scatter_object_list(out_object_list, in_object_list=None, src=0, group=None):
    g = _group(group)
    if g.nranks <= 1:
        out_object_list[:] = [in_object_list[0]] if in_object_list else []
        return
    gathered: List = []
    all_gather_object(gathered, in_object_list or [], group)
    src_in_group = g.get_group_rank(src) if src in g.ranks else src
    out_object_list[:] = [gathered[src_in_group][g.rank]]


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    g = _group(group)
    if g.nranks <= 1:
        if gather_list is not None:
            gather_list.append(Tensor(tensor._data))
        return _Task([])
    tmp: List[Tensor] = []
    all_gather(tmp, tensor, group)
    if gather_list is not None:
        gather_list.extend(tmp)
    return _Task(tmp)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    g = _group(group)
    if g.nranks <= 1:
        out_tensor_list.extend(Tensor(t._data) for t in in_tensor_list)
        return _Task(out_tensor_list)
    stacked = Tensor(jnp.stack([t._data for t in in_tensor_list]))
    gathered: List[Tensor] = []
    all_gather(gathered, stacked, group)  # [ranks][ranks, ...]
    for r in range(g.nranks):
        out_tensor_list.append(Tensor(gathered[r]._data[g.rank]))
    return _Task(out_tensor_list)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None, out_split_sizes=None,
                    group=None, sync_op=True):
    g = _group(group)
    if g.nranks <= 1:
        out_tensor._data = in_tensor._data
        return _Task([out_tensor])
    ins = list(jnp.split(in_tensor._data, g.nranks, axis=0))
    outs: List[Tensor] = []
    alltoall(outs, [Tensor(t) for t in ins], group)
    out_tensor._data = jnp.concatenate([t._data for t in outs], axis=0)
    return _Task([out_tensor])


def _p2p_pair(tensor, src, dst, group: Group):
    """Matched-pair p2p (ref `send_v2`/`recv_v2` over NCCL): the two endpoints
    execute one shared 2-device permute program; only src and dst participate.

    The exchange is a jitted copy over a 2-rank mesh — dst's row of the global
    array is replaced by src's — so, like the reference, a send with no matching
    recv (or mismatched shapes/dtypes) blocks."""
    pair = sorted({src, dst})
    me = group.ranks[group.rank]
    sub = Group(pair.index(me), -1, pair)
    garr, mesh = _to_global(tensor._data, sub)
    si, di = sub.get_group_rank(src), sub.get_group_rank(dst)
    perm = np.arange(sub.nranks)
    perm[di] = si
    fn = _replicated_jit("p2p", lambda a, p: a[p], mesh)
    return np.asarray(fn(garr, jnp.asarray(perm)))[di]


def send(tensor, dst=0, group=None, sync_op=True):
    """Send to global rank dst.  Must be paired with a `recv` on dst (matched
    pairs, same shape/dtype — reference `send_v2` semantics)."""
    g = _group(group)
    if g.nranks <= 1:
        return _Task([])
    if not _multiproc():
        raise RuntimeError(
            "eager p2p send across ranks needs jax.distributed (launch via "
            "paddle_tpu.distributed.launch); inside jit use ppermute/shard_map")
    if not g.is_member():
        raise RuntimeError(f"send: this rank is not a member of {g}")
    me = g.ranks[g.rank]
    _p2p_pair(tensor, me, dst, g)
    return _Task([])


def recv(tensor, src=0, group=None, sync_op=True):
    """Receive from global rank src into `tensor` (in-place; matched with a
    `send` on src)."""
    g = _group(group)
    if g.nranks <= 1:
        return _Task([tensor])
    if not _multiproc():
        raise RuntimeError("eager p2p recv across ranks needs jax.distributed")
    if not g.is_member():
        raise RuntimeError(f"recv: this rank is not a member of {g}")
    me = g.ranks[g.rank]
    tensor._data = jnp.asarray(_p2p_pair(tensor, 0 if src is None else src, me, g))
    return _Task([tensor])


def isend(tensor, dst, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=None, group=None):
    return recv(tensor, src, group, sync_op=False)


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    tasks = []
    for op in p2p_op_list:
        tasks.append(op.op(op.tensor, op.peer, op.group))
    return tasks
