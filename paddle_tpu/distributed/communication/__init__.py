from .group import (Group, barrier, destroy_process_group, get_backend, get_group,  # noqa
                    is_initialized, new_group, wait)
from .ops import (all_gather, all_gather_object, all_reduce, alltoall,  # noqa
                  alltoall_single, broadcast, broadcast_object_list, gather,
                  irecv, isend, recv, reduce, reduce_scatter, scatter,
                  scatter_object_list, send, ReduceOp, P2POp, batch_isend_irecv)
from . import stream  # noqa
