"""Process groups (reference: `python/paddle/distributed/communication/group.py:22`,
`collective.py:175` `new_group`).

A Group is a named set of global ranks.  On TPU there is no per-group NCCL
communicator to build — a group materializes as a mesh axis for XLA collectives; eager
collectives route through `communication.all_reduce` etc., which pick the jit'd
collective over the group's device set.
"""
from __future__ import annotations

from typing import List, Optional


class Group:
    def __init__(self, rank_in_group: int, gid: int, ranks: List[int], name=None):
        self._rank_in_group = rank_in_group
        self._id = gid
        self._ranks = list(ranks)
        self._name = name or f"group_{gid}"

    @property
    def rank(self):
        return self._rank_in_group

    @property
    def ranks(self):
        return self._ranks

    @property
    def nranks(self):
        return len(self._ranks)

    world_size = nranks

    @property
    def id(self):
        return self._id

    @property
    def name(self):
        return self._name

    def is_member(self):
        return self._rank_in_group >= 0

    def get_group_rank(self, global_rank):
        return self._ranks.index(global_rank) if global_rank in self._ranks else -1

    def __repr__(self):
        return f"Group(id={self._id}, ranks={self._ranks}, rank={self._rank_in_group})"

    # Task-style handle compat: eager collectives are synchronous under XLA's async
    # runtime (dispatch is async, completion on use) so wait() is a no-op.
    def process_group(self):
        return self


_group_map = {}
_group_counter = 0
_default_group: Optional[Group] = None


def _init_default_group(env):
    global _default_group, _group_counter
    ranks = list(range(env.world_size))
    _default_group = Group(env.rank, 0, ranks, "default")
    _group_map[0] = _default_group
    _group_counter = 0
    return _default_group


def _get_global_group() -> Group:
    global _default_group
    if _default_group is None:
        from ..parallel_env import ParallelEnv
        _init_default_group(ParallelEnv())
    return _default_group


def _get_or_throw_group_rank(rank, group):
    return group.get_group_rank(rank)


def new_group(ranks=None, backend=None, timeout=None):
    """reference `collective.py:175`."""
    global _group_counter
    from ..parallel_env import ParallelEnv
    env = ParallelEnv()
    if ranks is None:
        ranks = list(range(env.world_size))
    _group_counter += 1
    gid = _group_counter
    rank_in_group = ranks.index(env.rank) if env.rank in ranks else -1
    g = Group(rank_in_group, gid, sorted(ranks))
    _group_map[gid] = g
    return g


def get_group(gid=0):
    return _group_map.get(gid)


def is_initialized():
    from .. import parallel_env
    return parallel_env._is_initialized()


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _group_map.clear()
        _default_group = None
    else:
        _group_map.pop(group.id, None)


def get_backend(group=None):
    return "XLA"


def wait(tensor, group=None, use_calc_stream=True):
    # XLA runtime orders collectives on the stream; block for explicit sync
    import jax
    if hasattr(tensor, "_data"):
        jax.block_until_ready(tensor._data)


def barrier(group=None):
    from .ops import all_reduce
    from ...ops.creation import ones
    t = ones([1], "float32")
    all_reduce(t, group=group)
    wait(t)
