"""stream.* collective variants (reference:
`python/paddle/distributed/communication/stream/`).

The use_calc_stream distinction is meaningless under the XLA runtime (it owns stream
scheduling), so these delegate to the standard collectives, keeping the API surface.
"""
from .ops import (all_gather, all_reduce, alltoall, alltoall_single, broadcast,  # noqa
                  gather, recv, reduce, reduce_scatter, scatter, send)
