"""ParallelEnv + process bootstrap.

Reference parity: `python/paddle/distributed/parallel.py` (`ParallelEnv`,
`init_parallel_env` :915) and the TCPStore rendezvous (:1077).

TPU-native: rank/world come from the reference's env-var contract
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER, set by our launch CLI);
multi-host bring-up delegates to `jax.distributed.initialize`, whose coordination
service replaces TCPStore/gen_comm_id.  Collectives then ride ICI/DCN via XLA.
"""
from __future__ import annotations

import os

import jax


class ParallelEnv:
    def __init__(self):
        self._rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        endpoints = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = endpoints.split(",") if endpoints else []
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")
        self._device_id = int(os.getenv("FLAGS_selected_tpus",
                                        os.getenv("FLAGS_selected_gpus", "0")))
        self._nrings = int(os.getenv("FLAGS_nccl_nrings", "1"))

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints

    @property
    def nrings(self):
        return self._nrings

    # legacy aliases
    local_rank = rank
    nranks = world_size
    dev_id = device_id


_initialized = False


def _is_initialized():
    return _initialized


def init_parallel_env():
    """Bring up the distributed runtime (reference `init_parallel_env` :915).

    Multi-host: jax.distributed.initialize against PADDLE_MASTER (the coordination
    service is the TCPStore analog).  Single-process: no-op — collectives degrade to
    identity, exactly like the reference with nranks==1.
    """
    global _initialized
    if _initialized:
        return ParallelEnv()
    env = ParallelEnv()
    # local-cluster simulation (the reference's TestDistBase pattern,
    # test/legacy_test/test_dist_base.py:962): trainer processes pin the CPU
    # backend BEFORE jax initializes so the single real TPU isn't fought over
    if os.getenv("PADDLE_DIST_DEVICE", "").lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if env.world_size > 1 and os.getenv("PADDLE_DIST_BACKEND", "xla") == "xla":
        master = os.getenv("PADDLE_MASTER")
        if master is None and env.trainer_endpoints:
            master = env.trainer_endpoints[0]
        if master:
            host, _, port = master.partition(":")
            coord = f"{host}:{int(port) + 7}"
            try:
                jax.distributed.initialize(coordinator_address=coord,
                                           num_processes=env.world_size,
                                           process_id=env.rank)
            # tpu-lint: disable=TPL006 -- multi-process init is best-effort (already-initialized, single-host sim, no coordinator); degrades to local mode with a warning
            except Exception as e:  # already initialized or single-host sim
                if "already" not in str(e):
                    import warnings
                    warnings.warn(f"jax.distributed.initialize failed: {e}; "
                                  "continuing in local mode")
    _initialized = True
    from .communication.group import _init_default_group
    _init_default_group(env)
    return env


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank(ParallelEnv().rank)
    return ParallelEnv().rank


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return ParallelEnv().world_size
