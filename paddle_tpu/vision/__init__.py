from . import datasets, models, ops, transforms  # noqa

def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"
