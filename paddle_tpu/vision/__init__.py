from . import datasets, models, ops, transforms  # noqa

def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"


def image_load(path, backend=None):
    """ref vision/image.py image_load."""
    try:
        from PIL import Image
        return Image.open(path)
    except ImportError:
        import numpy as np
        raise RuntimeError("image_load needs PIL (not available in this build)")
