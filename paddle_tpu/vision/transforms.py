"""Vision transforms (reference: `python/paddle/vision/transforms/`) — numpy CHW
pipelines."""
from __future__ import annotations

import numbers

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr / 255.0


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        c = arr.shape[0] if self.data_format == "CHW" else arr.shape[-1]
        mean = self.mean[:c]
        std = self.std[:c]
        if self.data_format == "CHW":
            return (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
        return (arr - mean) / std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            arr = arr.transpose(1, 2, 0)
        h, w = arr.shape[:2]
        th, tw = self.size
        yi = (np.arange(th) * (h / th)).astype(np.int64).clip(0, h - 1)
        xi = (np.arange(tw) * (w / tw)).astype(np.int64).clip(0, w - 1)
        out = arr[yi][:, xi]
        if chw:
            out = out.transpose(2, 0, 1)
        return out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        y0 = max((h - th) // 2, 0)
        x0 = max((w - tw) // 2, 0)
        return arr[:, y0:y0 + th, x0:x0 + tw] if chw else arr[y0:y0 + th, x0:x0 + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, keys=None, **kw):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if self.padding:
            p = self.padding
            pad = ((0, 0), (p, p), (p, p)) if chw else ((p, p), (p, p), (0, 0))[:arr.ndim]
            arr = np.pad(arr, pad[:arr.ndim])
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        y0 = np.random.randint(0, max(h - th, 0) + 1)
        x0 = np.random.randint(0, max(w - tw, 0) + 1)
        return arr[:, y0:y0 + th, x0:x0 + tw] if chw else arr[y0:y0 + th, x0:x0 + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img)
            return arr[..., ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img)
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
            return (arr[:, ::-1] if chw else arr[::-1]).copy()
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
