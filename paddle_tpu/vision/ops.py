"""Vision ops (reference: `python/paddle/vision/ops.py` — roi_align, nms,
deform_conv2d, box ops)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply, _to_data


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Greedy NMS — host-side (dynamic output), like the reference CPU kernel."""
    b = np.asarray(_to_data(boxes))
    s = np.asarray(_to_data(scores)) if scores is not None else np.arange(len(b))[::-1]
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.maximum(0, xx2 - xx1) * np.maximum(0, yy2 - yy1)
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def box_iou(boxes1, boxes2):
    def f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None] - inter + 1e-10)
    return apply("box_iou", f, boxes1, boxes2)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1,
              aligned=True, name=None):
    """RoIAlign via bilinear grid sampling (reference phi `roi_align` kernel)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, rois, rois_num):
        n, c, h, w = feat.shape
        box_batch = jnp.repeat(jnp.arange(rois_num.shape[0]), 0)  # placeholder
        # build batch index per roi from boxes_num
        idx = jnp.concatenate([jnp.full((int(rois_num[i]),), i, jnp.int32)
                               for i in range(rois_num.shape[0])]) \
            if False else jnp.zeros((rois.shape[0],), jnp.int32)
        # boxes_num is static in eager; compute on host
        counts = np.asarray(rois_num)
        idx = jnp.asarray(np.repeat(np.arange(len(counts)), counts).astype(np.int32))
        offset = 0.5 if aligned else 0.0

        def one_roi(roi, bi):
            x1, y1, x2, y2 = roi * spatial_scale - offset
            bw = jnp.maximum(x2 - x1, 1e-6)
            bh = jnp.maximum(y2 - y1, 1e-6)
            ys = y1 + (jnp.arange(oh) + 0.5) * bh / oh
            xs = x1 + (jnp.arange(ow) + 0.5) * bw / ow
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            img = feat[bi]
            y0 = jnp.floor(gy).astype(jnp.int32)
            x0 = jnp.floor(gx).astype(jnp.int32)
            wy = gy - y0
            wx = gx - x0

            def at(yy, xx):
                yc = jnp.clip(yy, 0, h - 1)
                xc = jnp.clip(xx, 0, w - 1)
                return img[:, yc, xc]
            out = (at(y0, x0) * ((1 - wy) * (1 - wx))[None]
                   + at(y0, x0 + 1) * ((1 - wy) * wx)[None]
                   + at(y0 + 1, x0) * (wy * (1 - wx))[None]
                   + at(y0 + 1, x0 + 1) * (wy * wx)[None])
            return out
        return jax.vmap(one_roi)(rois, idx)
    return apply("roi_align", f, x, boxes, boxes_num)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    raise NotImplementedError("deform_conv2d: planned (gather-based Pallas kernel)")
