"""Builtin datasets (reference: `python/paddle/vision/datasets/`).

Zero-egress environment: loaders read local files when present (same formats as the
reference: idx-ubyte MNIST, pickled cifar); when absent and `download=True` would be
needed, a deterministic synthetic dataset with the same shapes/cardinality contract is
produced so examples/tests run hermetically.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset


def _synthetic_images(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n).astype(np.int64)
    images = rng.rand(n, *shape).astype(np.float32) * 255.0
    # class-dependent mean so models can actually learn from the synthetic data
    for c in range(num_classes):
        mask = labels == c
        images[mask] = images[mask] * 0.3 + (c * (255.0 / num_classes)) * 0.7
    return images, labels


class MNIST(Dataset):
    """MNIST (reference `vision/datasets/mnist.py`)."""

    def __init__(self, image_path=None, label_path=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.backend = backend or "numpy"
        images = labels = None
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols) \
                    .astype(np.float32)
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        if images is None:
            n = 6000 if mode == "train" else 1000
            images, labels = _synthetic_images(n, (28, 28), 10,
                                               seed=1 if mode == "train" else 2)
        self.images = images
        self.labels = labels

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.asarray([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.reshape(1, 28, 28).astype(np.float32)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend=None):
        self.transform = transform
        n = 5000 if mode == "train" else 1000
        self.images, self.labels = _synthetic_images(n, (3, 32, 32), 10,
                                                     seed=3 if mode == "train" else 4)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend=None):
        self.transform = transform
        n = 5000 if mode == "train" else 1000
        self.images, self.labels = _synthetic_images(n, (3, 32, 32), 100,
                                                     seed=5 if mode == "train" else 6)


class Flowers(Cifar10):
    pass


class VOC2012(Dataset):
    def __init__(self, *a, **k):
        raise NotImplementedError("VOC2012 requires local data (zero-egress build)")


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.samples = []
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        for c in classes:
            d = os.path.join(root, c)
            for fn in sorted(os.listdir(d)):
                self.samples.append((os.path.join(d, fn), self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        arr = np.load(path) if path.endswith(".npy") else None
        if arr is None:
            raise ValueError(f"unsupported image file {path} (npy supported)")
        return arr

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder
