from .lenet import LeNet  # noqa
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152, wide_resnet50_2  # noqa
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa
from .mobilenetv2 import MobileNetV2, mobilenet_v2  # noqa
from .alexnet import AlexNet, alexnet  # noqa
