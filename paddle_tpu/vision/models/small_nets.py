"""SqueezeNet, MobileNetV1/V3, ShuffleNetV2, GoogLeNet, InceptionV3, DenseNet
(reference: `python/paddle/vision/models/{squeezenet,mobilenetv1,mobilenetv3,
shufflenetv2,googlenet,inceptionv3,densenet}.py` — architectures per the
original papers; pretrained weights are not bundled, matching a from-scratch
framework)."""
from ... import nn
from ...ops.manipulation import concat, reshape, transpose


def _conv_bn(in_c, out_c, k, stride=1, padding=0, groups=1, act="relu"):
    layers = [nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act == "relu":
        layers.append(nn.ReLU())
    elif act == "hardswish":
        layers.append(nn.Hardswish())
    elif act == "swish":
        layers.append(nn.Swish())
    elif act != "none":
        raise ValueError(f"unsupported activation: {act!r}")
    return nn.Sequential(*layers)


# ---------------------------------------------------------------------------
# SqueezeNet
# ---------------------------------------------------------------------------

class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_c, squeeze, 1), nn.ReLU())
        self.e1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.e3 = nn.Sequential(nn.Conv2D(squeeze, e3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        s = self.squeeze(x)
        return concat([self.e1(s), self.e3(s)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D((1, 1)))

    def forward(self, x):
        x = self.classifier(self.features(x))
        return x.flatten(1)


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)


# ---------------------------------------------------------------------------
# MobileNetV1
# ---------------------------------------------------------------------------

class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        c0 = int(32 * scale)
        layers = [_conv_bn(3, c0, 3, stride=2, padding=1)]
        for in_c, out_c, s in cfg:
            ic, oc = int(in_c * scale), int(out_c * scale)
            layers += [_conv_bn(ic, ic, 3, stride=s, padding=1, groups=ic),
                       _conv_bn(ic, oc, 1)]
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.pool(self.features(x)).flatten(1)
        return self.fc(x)


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


# ---------------------------------------------------------------------------
# MobileNetV3
# ---------------------------------------------------------------------------

class _SE(nn.Layer):
    def __init__(self, c, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(c, c // r, 1)
        self.fc2 = nn.Conv2D(c // r, c, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, in_c, exp, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp != in_c:
            layers.append(_conv_bn(in_c, exp, 1, act=act))
        layers.append(_conv_bn(exp, exp, k, stride=stride, padding=k // 2,
                               groups=exp, act=act))
        if use_se:
            layers.append(_SE(exp))
        layers.append(_conv_bn(exp, out_c, 1, act="none"))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_LARGE = [
    (16, 16, 16, 3, 1, False, "relu"), (16, 64, 24, 3, 2, False, "relu"),
    (24, 72, 24, 3, 1, False, "relu"), (24, 72, 40, 5, 2, True, "relu"),
    (40, 120, 40, 5, 1, True, "relu"), (40, 120, 40, 5, 1, True, "relu"),
    (40, 240, 80, 3, 2, False, "hardswish"),
    (80, 200, 80, 3, 1, False, "hardswish"),
    (80, 184, 80, 3, 1, False, "hardswish"),
    (80, 184, 80, 3, 1, False, "hardswish"),
    (80, 480, 112, 3, 1, True, "hardswish"),
    (112, 672, 112, 3, 1, True, "hardswish"),
    (112, 672, 160, 5, 2, True, "hardswish"),
    (160, 960, 160, 5, 1, True, "hardswish"),
    (160, 960, 160, 5, 1, True, "hardswish")]

_V3_SMALL = [
    (16, 16, 16, 3, 2, True, "relu"), (16, 72, 24, 3, 2, False, "relu"),
    (24, 88, 24, 3, 1, False, "relu"), (24, 96, 40, 5, 2, True, "hardswish"),
    (40, 240, 40, 5, 1, True, "hardswish"),
    (40, 240, 40, 5, 1, True, "hardswish"),
    (40, 120, 48, 5, 1, True, "hardswish"),
    (48, 144, 48, 5, 1, True, "hardswish"),
    (48, 288, 96, 5, 2, True, "hardswish"),
    (96, 576, 96, 5, 1, True, "hardswish"),
    (96, 576, 96, 5, 1, True, "hardswish")]


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_c, num_classes=1000, scale=1.0,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        sc = lambda c: _make_divisible(c * scale)  # noqa: E731
        layers = [_conv_bn(3, sc(16), 3, stride=2, padding=1, act="hardswish")]
        for in_c, exp, out_c, k, s, se, act in cfg:
            layers.append(_MBV3Block(sc(in_c), sc(exp), sc(out_c), k, s, se,
                                     act))
        last_exp = sc(cfg[-1][1])
        layers.append(_conv_bn(sc(cfg[-1][2]), last_exp, 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_exp, last_c), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 1280, num_classes, scale, with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 1024, num_classes, scale, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


# ---------------------------------------------------------------------------
# ShuffleNetV2
# ---------------------------------------------------------------------------

def _channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [n, c, h, w])


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 2:
            self.branch1 = nn.Sequential(
                _conv_bn(in_c, in_c, 3, stride=2, padding=1, groups=in_c,
                         act="none"),
                _conv_bn(in_c, branch_c, 1, act=act))
            in_b2 = in_c
        else:
            self.branch1 = None
            in_b2 = in_c // 2
        self.branch2 = nn.Sequential(
            _conv_bn(in_b2, branch_c, 1, act=act),
            _conv_bn(branch_c, branch_c, 3, stride=stride, padding=1,
                     groups=branch_c, act="none"),
            _conv_bn(branch_c, branch_c, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CFG = {0.25: (24, 48, 96, 512), 0.33: (32, 64, 128, 512),
                0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
                1.5: (176, 352, 704, 1024), 2.0: (244, 488, 976, 2048)}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        c1, c2, c3, c4 = _SHUFFLE_CFG[scale]
        self.conv1 = _conv_bn(3, 24, 3, stride=2, padding=1, act=act)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = 24
        for out_c, repeat in ((c1, 4), (c2, 8), (c3, 4)):
            units = [_ShuffleUnit(in_c, out_c, 2, act)]
            for _ in range(repeat - 1):
                units.append(_ShuffleUnit(out_c, out_c, 1, act))
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv5 = _conv_bn(c3, c4, 1, act=act)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(c4, num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv5(self.stages(x))
        return self.fc(self.pool(x).flatten(1))


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(1.0, act="swish", **kwargs)


# ---------------------------------------------------------------------------
# GoogLeNet / InceptionV3
# ---------------------------------------------------------------------------

class _Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, pj):
        super().__init__()
        self.b1 = _conv_bn(in_c, c1, 1)
        self.b2 = nn.Sequential(_conv_bn(in_c, c3r, 1),
                                _conv_bn(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_conv_bn(in_c, c5r, 1),
                                _conv_bn(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _conv_bn(in_c, pj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _conv_bn(3, 64, 7, stride=2, padding=3), nn.MaxPool2D(3, 2, padding=1),
            _conv_bn(64, 64, 1), _conv_bn(64, 192, 3, padding=1),
            nn.MaxPool2D(3, 2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.dropout = nn.Dropout(0.2)
        self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.pool4(self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x))))))
        x = self.i5b(self.i5a(x))
        x = self.dropout(self.pool(x).flatten(1))
        out = self.fc(x)
        # reference returns (out, aux1, aux2); aux heads inactive at eval
        return out, out, out


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)


class InceptionV3(nn.Layer):
    """Simplified InceptionV3 trunk (stem + inception stacks + head) — the
    reference topology with the factorized 7x7 branches folded to 3x3 pairs."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _conv_bn(3, 32, 3, stride=2), _conv_bn(32, 32, 3),
            _conv_bn(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _conv_bn(64, 80, 1), _conv_bn(80, 192, 3), nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _Inception(192, 64, 48, 64, 64, 96, 32),
            _Inception(256, 64, 48, 64, 64, 96, 64),
            _Inception(288, 64, 48, 64, 64, 96, 64),
            nn.MaxPool2D(3, 2),
            _Inception(288, 192, 128, 192, 128, 192, 192),
            _Inception(768, 192, 160, 192, 160, 192, 192),
            nn.MaxPool2D(3, 2),
            _Inception(768, 320, 192, 384, 192, 384, 192))
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.dropout = nn.Dropout(0.5)
        self.fc = nn.Linear(1280, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        return self.fc(self.dropout(self.pool(x).flatten(1)))


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)


# ---------------------------------------------------------------------------
# DenseNet
# ---------------------------------------------------------------------------

class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth, bn_size):
        super().__init__()
        self.fn = nn.Sequential(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1, bias_attr=False))

    def forward(self, x):
        return concat([x, self.fn(x)], axis=1)


_DENSE_CFG = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
              169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
              264: (6, 12, 64, 48)}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, growth_rate=32, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        if layers == 161:
            growth_rate, init_c = 48, 96
        else:
            init_c = 64
        cfg = _DENSE_CFG[layers]
        feats = [_conv_bn(3, init_c, 7, stride=2, padding=3),
                 nn.MaxPool2D(3, 2, padding=1)]
        c = init_c
        for i, n in enumerate(cfg):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth_rate, bn_size))
                c += growth_rate
            if i != len(cfg) - 1:
                feats += [nn.BatchNorm2D(c), nn.ReLU(),
                          nn.Conv2D(c, c // 2, 1, bias_attr=False),
                          nn.AvgPool2D(2, 2)]
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.dropout_p = dropout
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(dropout) if dropout > 0 else None
            self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            if self.dropout is not None:
                x = self.dropout(x)
            x = self.fc(x)
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(264, **kwargs)
