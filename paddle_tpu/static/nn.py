"""paddle.static.nn — static-graph control flow + layer helpers.

Reference parity: `python/paddle/static/nn/` (cond/case/switch_case/while_loop
build ConditionalBlock/While ops; fc/embedding/batch_norm build layers
inline).

TPU-native: under eager execution with concrete values, control flow is plain
Python (the reference's dygraph convert_* behavior).  Under `to_static`
capture the predicates are tracers: `cond`/`case`/`switch_case` evaluate both
branches and select (functional branches — XLA DCEs the untaken side when the
predicate folds), and `while_loop` lowers to `jax.lax.while_loop`, giving REAL
data-dependent trip counts inside the compiled program (forward/inference;
reverse-mode through a dynamic while is unsupported, as jax defines).
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply, _to_data


def _is_traced(x) -> bool:
    d = x._data if isinstance(x, Tensor) else x
    return isinstance(d, jax.core.Tracer)


def _tree_select(pred, t_out, f_out):
    flat_t, tdef = jax.tree_util.tree_flatten(
        t_out, is_leaf=lambda x: isinstance(x, Tensor))
    flat_f, _ = jax.tree_util.tree_flatten(
        f_out, is_leaf=lambda x: isinstance(x, Tensor))
    outs = []
    for a, b in zip(flat_t, flat_f):
        outs.append(apply("cond_select",
                          lambda p, x, y: jnp.where(p, x, y), pred, a, b))
    return jax.tree_util.tree_unflatten(tdef, outs)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """ref static.nn.cond: data-dependent branch."""
    if not _is_traced(pred):
        taken = bool(_to_data(pred))
        if taken:
            return true_fn() if true_fn is not None else None
        return false_fn() if false_fn is not None else None
    t_out = true_fn() if true_fn is not None else None
    f_out = false_fn() if false_fn is not None else None
    if t_out is None and f_out is None:
        return None
    return _tree_select(pred, t_out, f_out)


def case(pred_fn_pairs, default=None, name=None):
    """ref static.nn.case: first true predicate wins."""
    if not pred_fn_pairs:
        return default() if default else None
    (pred, fn), *rest = pred_fn_pairs
    return cond(pred, fn,
                (lambda: case(rest, default)) if (rest or default) else None)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """ref static.nn.switch_case: integer-indexed dispatch."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    if not _is_traced(branch_index):
        idx = int(_to_data(branch_index))
        for k, fn in items:
            if k == idx:
                return fn()
        return default() if default else None
    pairs = [(apply("eq", lambda b: b == k, branch_index), fn)
             for k, fn in items]
    return case(pairs, default)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence,
               is_test: bool = False, name=None) -> List:
    """ref static.nn.while_loop: data-dependent loop.

    Eager (concrete values): a Python loop, exactly the reference's dygraph
    convert_while_loop.  Under capture: `jax.lax.while_loop` — the trip count
    stays data-dependent inside the compiled program."""
    vars_t = [v if isinstance(v, Tensor) else Tensor(_to_data(v))
              for v in loop_vars]
    traced = any(_is_traced(v) for v in vars_t) or \
        _is_traced(cond_fn(*vars_t))
    if not traced:
        while bool(_to_data(cond_fn(*vars_t))):
            out = body_fn(*vars_t)
            out = out if isinstance(out, (list, tuple)) else [out]
            vars_t = [v if isinstance(v, Tensor) else Tensor(_to_data(v))
                      for v in out]
        return list(vars_t)

    def c(datas):
        r = cond_fn(*[Tensor(d) for d in datas])
        return (r._data if isinstance(r, Tensor) else jnp.asarray(r)).reshape(())

    def b(datas):
        out = body_fn(*[Tensor(d) for d in datas])
        out = out if isinstance(out, (list, tuple)) else [out]
        return tuple(o._data if isinstance(o, Tensor) else jnp.asarray(o)
                     for o in out)

    res = jax.lax.while_loop(c, b, tuple(v._data for v in vars_t))
    return [Tensor(r) for r in res]


# ---- layer helpers (ref static/nn/common.py; thin over the eager layers) ----

def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from ..ops.manipulation import reshape
    from ..ops.creation import create_parameter
    from ..ops.math import matmul
    import numpy as np
    xt = x if isinstance(x, Tensor) else Tensor(_to_data(x))
    shp = xt.shape
    in_f = int(np.prod(shp[num_flatten_dims:]))
    x2 = reshape(xt, list(shp[:num_flatten_dims]) + [in_f])
    from . import create_parameter as static_create_parameter
    w = static_create_parameter([in_f, size], "float32")
    out = matmul(x2, w)
    if bias_attr is not False:
        b = static_create_parameter([size], "float32", is_bias=True)
        out = out + b
    if activation == "relu":
        from ..nn.functional.activation import relu
        out = relu(out)
    elif activation == "tanh":
        from ..ops.math import tanh
        out = tanh(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None,
              dtype="float32"):
    from . import create_parameter as static_create_parameter
    table = static_create_parameter(list(size), dtype)
    return apply("embedding", lambda t, i: t[i.astype(jnp.int32)],
                 table, input)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, **kwargs):
    from ..nn.functional.norm import normalize
    out = apply("static_bn",
                lambda x: (x - jnp.mean(x, axis=0, keepdims=True)) /
                jnp.sqrt(jnp.var(x, axis=0, keepdims=True) + epsilon), input)
    if act == "relu":
        from ..nn.functional.activation import relu
        out = relu(out)
    return out


__all__ = ["cond", "case", "switch_case", "while_loop", "fc", "embedding",
           "batch_norm"]
