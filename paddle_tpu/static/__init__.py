"""paddle.static — the static-graph surface, executable.

Reference parity: `python/paddle/static/` (Program/Executor over ProgramDesc,
`fluid/framework/program_desc.h:32`, `new_executor/standalone_executor.h:34`).

TPU-native design: there is no ProgramDesc protobuf — under
`paddle.enable_static()` every eager op dispatch additionally records
(name, jfn, inputs, outputs) into the current `Program` (see
`core/tensor.py:_static_recorder`).  `Executor.run` re-executes the recorded op
list with feed values substituted into the placeholder tensors and rebinds each
recorded output, so parameters persist across `run` calls and
`Optimizer.minimize` (recorded as a train-op closure) updates them — the
standalone-executor behavior with the tape as the program IR.
"""
from __future__ import annotations

import contextlib
import pickle
from typing import Any, Dict, List

import numpy as np

from .input_spec import InputSpec  # noqa
from ..core.tensor import Tensor, _static_recorder, _to_data


class Variable(Tensor):
    """Alias: static Variables are Tensors here (ref framework.Variable)."""


class Program:
    """Recorded op list + placeholder registry (ref ProgramDesc)."""

    def __init__(self):
        self.ops: List[Any] = []          # ("op", name, jfn, inputs, outputs)
                                          # | ("py", fn)
        self.placeholders: Dict[str, Tensor] = {}
        self.params: List[Tensor] = []
        self.random_seed = 0

    # -- recorder hooks --
    def _record(self, name, jfn, inputs, res):
        outs = res if isinstance(res, tuple) else (res,)
        self.ops.append(("op", name, jfn, list(inputs), list(outs)))

    def _record_py(self, fn):
        self.ops.append(("py", fn))

    # -- ProgramDesc-surface compat --
    def global_block(self):
        return self

    def clone(self, for_test=False):
        if not for_test:
            return self
        p = Program()
        # test clone: drop train-ops (backward/optimizer closures)
        p.ops = [op for op in self.ops if op[0] == "op"]
        p.placeholders = self.placeholders
        p.params = self.params
        return p

    def list_vars(self):
        return list(self.placeholders.values()) + list(self.params)

    def all_parameters(self):
        return list(self.params)

    @property
    def blocks(self):
        return [self]


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    prev_m, prev_s = _main_program, _startup_program
    prev_rec = _static_recorder[0]
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    if prev_rec is not None:          # static mode on: record into the guard's
        _static_recorder[0] = main_program
    try:
        yield
    finally:
        _main_program = prev_m
        _startup_program = prev_s
        _static_recorder[0] = prev_rec


def _enable_static_recording():
    _static_recorder[0] = _main_program


def _disable_static_recording():
    _static_recorder[0] = None


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed placeholder (ref static.data)."""
    import jax.numpy as jnp
    from ..core import dtype as _dt
    shp = [1 if (s is None or s == -1) else s for s in shape]
    t = Tensor(jnp.zeros(shp, _dt.to_np(dtype)))
    t.name = name
    if dtype in ("float32", "float64", "float16", "bfloat16"):
        t.stop_gradient = False
    _main_program.placeholders[name] = t
    return t


class Scope:
    """Name -> variable map (ref framework.Scope)."""

    def __init__(self):
        self.vars: Dict[str, Tensor] = {}

    def var(self, name):
        return self.vars.setdefault(name, Tensor())

    def find_var(self, name):
        v = self.vars.get(name)
        if v is None:
            v = _main_program.placeholders.get(name)
        if v is None:
            for p in _main_program.params:
                if getattr(p, "name", None) == name:
                    return _VarWrap(p)
        return _VarWrap(v) if v is not None else None


class _VarWrap:
    def __init__(self, t):
        self._t = t

    def get_tensor(self):
        return np.asarray(self._t._data)


_scope = Scope()


def global_scope():
    return _scope


@contextlib.contextmanager
def scope_guard(scope):
    global _scope
    prev = _scope
    _scope = scope
    try:
        yield
    finally:
        _scope = prev


class Executor:
    """Re-executes a recorded Program (ref StandaloneExecutor)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, **kwargs):
        import jax.numpy as jnp
        prog = program or _main_program
        if isinstance(prog, CompiledProgram):
            prog = prog._program
        if isinstance(prog, _LoadedProgram):
            args = [jnp.asarray(_to_data((feed or {})[n]))
                    for n in prog.feed_names]
            outs = prog.exported.call(*args)
            outs = outs if isinstance(outs, (tuple, list)) else (outs,)
            return [np.asarray(o) if return_numpy else Tensor(o) for o in outs]
        # executing must not re-record
        prev = _static_recorder[0]
        _static_recorder[0] = None
        try:
            for name, val in (feed or {}).items():
                ph = prog.placeholders.get(name)
                if ph is None:
                    ph = _main_program.placeholders.get(name)
                if ph is None:
                    raise KeyError(f"feed target '{name}' is not a declared "
                                   "static.data placeholder")
                ph._data = jnp.asarray(_to_data(val))
                ph.grad = None   # feed grads never persist across runs
            from ..core.tensor import apply
            for op in prog.ops:
                if op[0] == "py":
                    op[1]()
                    continue
                _, name, jfn, inputs, outputs = op
                res = apply(name, jfn, *inputs)
                outs = res if isinstance(res, tuple) else (res,)
                for t, o in zip(outputs, outs):
                    t._data = o._data
                    t._grad_node = o._grad_node
                    t._out_index = o._out_index
            if fetch_list is None:
                return []
            out = []
            for t in fetch_list:
                out.append(np.asarray(t._data) if return_numpy else t)
            return out
        finally:
            _static_recorder[0] = prev

    def close(self):
        pass


class CompiledProgram:
    """ref CompiledProgram: XLA jit-compiles each re-executed op anyway, so this
    is a thin marker around Program."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def with_data_parallel(self, *a, **kw):
        return self


class BuildStrategy:
    def __init__(self):
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_optimizer_ops = False
        self.fuse_elewise_add_act_ops = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class IpuStrategy:
    def __init__(self):
        pass


class IpuCompiledProgram:
    def __init__(self, program=None, ipu_strategy=None, scope=None):
        self._program = program

    def compile(self, feed_list, fetch_list):
        return self._program


@contextlib.contextmanager
def device_guard(device=None):
    yield


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    yield


def set_ipu_shard(call_func, index=-1, stage=-1):
    return call_func


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """ref static Print op: logs at execution, passes the value through."""
    from ..core.tensor import apply
    import jax

    def f(x):
        jax.debug.print((message or "") + " {}", x)
        return x
    return apply("print", f, input)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """ref static py_func: host-python op."""
    from ..core.tensor import apply
    import jax.numpy as jnp
    xs = x if isinstance(x, (list, tuple)) else [x]

    def f(*datas):
        res = func(*[np.asarray(d) for d in datas])
        return jnp.asarray(res)
    return apply("py_func", f, *xs)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    import jax.numpy as jnp
    from ..core import dtype as _dt
    t = Tensor(jnp.full(shape, value, _dt.to_np(dtype)))
    t.persistable = persistable
    if name:
        t.name = name
    _main_program.params.append(t)
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    import jax
    import jax.numpy as jnp
    from ..core import dtype as _dt, generator as _gen
    from ..ops.creation import create_parameter as _create
    if default_initializer is None and not is_bias:
        # static default: fan-in uniform (the eager helper defaults to zeros)
        fan_in = shape[0] if shape else 1
        bound = (6.0 / max(fan_in, 1)) ** 0.5
        key = _gen.next_key()
        default_initializer = lambda t: t.set_value(  # noqa: E731
            jax.random.uniform(key, tuple(shape), _dt.to_np(dtype),
                               -bound, bound))
    p = _create(shape, dtype, name=name, attr=attr, is_bias=is_bias,
                default_initializer=default_initializer)
    p.stop_gradient = False
    if name:
        p.name = name
    _main_program.params.append(p)
    return p


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """ref static gradients: grads of targets w.r.t. inputs."""
    from ..core.autograd import grad as _grad
    ts = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    gs = _grad(ts, ins, grad_outputs=target_gradients, retain_graph=True,
               allow_unused=True)
    return list(gs)


def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None,
                    checkpoints=None):
    """ref append_backward: records the backward as a train op; grads land on
    param.grad after the next Executor.run."""
    params = parameter_list or _main_program.params

    def run_backward():
        loss.backward(retain_graph=True)
    _main_program._record_py(run_backward)
    return [(p, p.grad) for p in params]


def accuracy(input, label, k=1, correct=None, total=None):
    from ..ops.math import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=200, topk=1, slide_steps=1):
    import jax.numpy as jnp
    from ..metric import Auc
    m = Auc(num_thresholds=num_thresholds)
    m.update(np.asarray(input._data), np.asarray(label._data))
    return Tensor(jnp.asarray(m.accumulate(), jnp.float32))


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    raise NotImplementedError(
        "ctr_metric_bundle belongs to the parameter-server mode, which is "
        "descoped on TPU (see README scope notes)")


class ExponentialMovingAverage:
    """ref static ExponentialMovingAverage over parameters."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._ema: Dict[int, Any] = {}
        self._backup: Dict[int, Any] = {}
        self._step = 0

    def update(self):
        self._step += 1
        for p in _main_program.params:
            pid = id(p)
            prev = self._ema.get(pid, p._data)
            self._ema[pid] = self._decay * prev + (1 - self._decay) * p._data

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._data for p in _main_program.params}
        for p in _main_program.params:
            if id(p) in self._ema:
                p._data = self._ema[id(p)]
        try:
            yield
        finally:
            if need_restore:
                for p in _main_program.params:
                    p._data = self._backup[id(p)]

    def restore(self, executor=None):
        for p in _main_program.params:
            if id(p) in self._backup:
                p._data = self._backup[id(p)]


class WeightNormParamAttr:
    """ref WeightNormParamAttr (compat shell; weight-norm lives in
    nn.utils on the eager path)."""

    def __init__(self, dim=None, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.dim = dim
        self.name = name


def cpu_places(device_count=None):
    from ..core.place import CPUPlace
    return [CPUPlace() for _ in range(device_count or 1)]


def cuda_places(device_ids=None):
    from ..core.place import CUDAPlace
    ids = device_ids if device_ids is not None else [0]
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    from ..core.place import XPUPlace
    ids = device_ids if device_ids is not None else [0]
    return [XPUPlace(i) for i in ids]


# ---- serialization (ref static/io.py) ----

def _state(program):
    return {getattr(p, "name", f"param_{i}"): np.asarray(p._data)
            for i, p in enumerate(program.params)}


def serialize_program(feed_vars, fetch_vars, **kwargs):
    feeds = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetches = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    return pickle.dumps({"feeds": [t.name for t in feeds],
                         "fetch_shapes": [list(t.shape) for t in fetches]})


def serialize_persistables(feed_vars, fetch_vars, **kwargs):
    return pickle.dumps(_state(_main_program))


def deserialize_program(data):
    return pickle.loads(data)


def deserialize_persistables(program, data, executor=None):
    import jax.numpy as jnp
    state = pickle.loads(data)
    params = program.params if isinstance(program, Program) \
        else _main_program.params
    for i, p in enumerate(params):
        name = getattr(p, "name", f"param_{i}")
        if name in state:
            p._data = jnp.asarray(state[name])
    return state


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def save(program, model_path, protocol=4, **configs):
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(_state(program), f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    deserialize_persistables(program, pickle.dumps(state))


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    deserialize_persistables(program, pickle.dumps(state_dict))


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    return program


_inference_registry: Dict[str, Any] = {}


def _make_replay_fn(prog, feeds, fetches):
    """Functional interpreter over the recorded op list: feed arrays in,
    fetch arrays out.  Params and constants are closed over, so jax can trace
    and export it as one StableHLO program."""
    def fn(*feed_datas):
        env = {id(ph): d for ph, d in zip(feeds, feed_datas)}
        for op in prog.ops:
            if op[0] != "op":
                continue                  # train ops are not part of inference
            _, name, jfn, inputs, outputs = op
            datas = [env.get(id(x), x._data if isinstance(x, Tensor) else x)
                     for x in inputs]
            out = jfn(*datas)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for t, o in zip(outputs, outs):
                env[id(t)] = o
        return tuple(env.get(id(f), f._data) for f in fetches)
    return fn


class _LoadedProgram:
    """Deserialized inference program: Executor.run calls the compiled
    StableHLO artifact directly."""

    def __init__(self, exported, feed_names):
        self.exported = exported
        self.feed_names = feed_names
        self.placeholders: Dict[str, Tensor] = {}
        self.params: List[Tensor] = []
        self.ops: List[Any] = []


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Export the inference slice of the program as StableHLO (params baked in)
    so `load_inference_model` works across processes (ref
    save_inference_model -> ProgramDesc+persistables serialization)."""
    import os
    import jax
    from jax import export as jax_export
    prog = program or _main_program
    feeds = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetches = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    fn = _make_replay_fn(prog, feeds, fetches)
    specs = [jax.ShapeDtypeStruct(tuple(t._data.shape), t._data.dtype)
             for t in feeds]
    exported = jax_export.export(jax.jit(fn))(*specs)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({"params": _state(prog),
                     "feed_names": [t.name for t in feeds]}, f)
    _inference_registry[path_prefix] = (prog, feeds, fetches)


def load_inference_model(path_prefix, executor=None, **kwargs):
    from jax import export as jax_export
    with open(path_prefix + ".pdiparams", "rb") as f:
        payload = pickle.load(f)
    if path_prefix in _inference_registry:
        # same-process fast path: rehydrate the live program's params
        prog, feeds, fetches = _inference_registry[path_prefix]
        deserialize_persistables(prog, pickle.dumps(payload["params"]))
        return prog, payload["feed_names"], fetches
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    prog = _LoadedProgram(exported, payload["feed_names"])
    n_out = len(exported.out_avals)
    return prog, payload["feed_names"], list(range(n_out))


__all__ = [
    "BuildStrategy", "CompiledProgram", "ExecutionStrategy", "Executor",
    "ExponentialMovingAverage", "InputSpec", "IpuCompiledProgram", "IpuStrategy",
    "Print", "Program", "Variable", "WeightNormParamAttr", "accuracy",
    "append_backward", "auc", "cpu_places", "create_global_var",
    "create_parameter", "ctr_metric_bundle", "cuda_places", "data",
    "default_main_program", "default_startup_program",
    "deserialize_persistables", "deserialize_program", "device_guard",
    "global_scope", "gradients", "ipu_shard_guard", "load", "load_from_file",
    "load_inference_model", "load_program_state", "name_scope",
    "normalize_program", "program_guard", "py_func", "save",
    "save_inference_model", "save_to_file", "scope_guard",
    "serialize_persistables", "serialize_program", "set_ipu_shard",
    "set_program_state", "xpu_places", "nn",
]

from . import nn  # noqa  (static.nn control flow + layer helpers)
