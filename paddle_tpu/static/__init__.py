"""paddle.static compatibility surface.

The reference's static graph (ProgramDesc + executors) maps to jit/to_static capture
here; this module keeps the high-traffic static APIs importable: InputSpec, save/load
inference model (delegating to jit.save/load), and name-scoped data declarations.
"""
from __future__ import annotations

from .input_spec import InputSpec  # noqa


def data(name, shape, dtype="float32", lod_level=0):
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    from ..core import dtype as _dt
    import numpy as np
    shp = [1 if (s is None or s == -1) else s for s in shape]
    t = Tensor(jnp.zeros(shp, _dt.to_np(dtype)))
    t.name = name
    return t


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kwargs):
    raise NotImplementedError(
        "static-graph save_inference_model: use paddle_tpu.jit.save on a Layer (the "
        "to_static capture path replaces ProgramDesc serialization)")


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit import load
    return load(path_prefix)


class Program:
    """Placeholder Program object for API compat (the jaxpr is the real IR)."""

    def __init__(self):
        self._ops = []

    def global_block(self):
        return self


def default_main_program():
    return Program()


def default_startup_program():
    return Program()
