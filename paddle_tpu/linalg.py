"""paddle.linalg namespace (re-exports; reference `python/paddle/linalg.py`)."""
from .ops.linalg import *  # noqa
from .ops.linalg import (cholesky, cholesky_solve, cond, corrcoef, cov, det, eig,  # noqa
                         eigh, eigvals, eigvalsh, householder_product, inv, inverse,
                         lstsq, lu, matrix_norm, matrix_power, matrix_rank, multi_dot,
                         norm, pdist, pinv, qr, slogdet, solve, svd,
                         triangular_solve, vector_norm)
from .ops.math import matmul  # noqa
