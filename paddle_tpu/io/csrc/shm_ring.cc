// Shared-memory SPSC ring buffer for DataLoader worker->main batch transfer.
//
// Reference parity: `fluid/memory/allocation/mmap_allocator.{h,cc}` +
// `fluid/operators/reader/blocking_queue.h` — the reference moves worker
// batches through shared memory with a C++ blocking queue; this is the same
// design as one POSIX-shm ring per worker process.
//
// Layout: [Header | data bytes].  Single producer (worker), single consumer
// (main process).  Messages are framed [u64 len | payload], wrapping at the
// end of the data region.  Lock-free: head/tail are C++11 atomics in shared
// memory; blocking sides spin with exponential nanosleep backoff.
//
// C ABI (consumed via ctypes from paddle_tpu/io/shm_ring.py).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <errno.h>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

struct Header {
  std::atomic<uint64_t> head;   // consumer position (bytes consumed)
  std::atomic<uint64_t> tail;   // producer position (bytes produced)
  std::atomic<uint32_t> closed; // producer hung up
  uint32_t _pad;
  uint64_t capacity;            // data-region size in bytes
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  uint64_t map_len;
  int owner;
  char name[256];
};

inline uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000u + ts.tv_nsec / 1000000u;
}

inline void backoff(unsigned& spins) {
  if (spins < 64) {
    ++spins;
    return;                      // busy spin first
  }
  struct timespec ts = {0, spins < 1024 ? 50000 : 500000};  // 50us -> 500us
  nanosleep(&ts, nullptr);
  if (spins < 1024) spins *= 2;
}

// copy len bytes into the ring at logical position pos (wrapping)
inline void put_bytes(Ring* r, uint64_t pos, const void* src, uint64_t len) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = len < cap - off ? len : cap - off;
  memcpy(r->data + off, src, first);
  if (len > first) memcpy(r->data, (const uint8_t*)src + first, len - first);
}

inline void get_bytes(Ring* r, uint64_t pos, void* dst, uint64_t len) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = len < cap - off ? len : cap - off;
  memcpy(dst, r->data + off, first);
  if (len > first) memcpy((uint8_t*)dst + first, r->data, len - first);
}

Ring* open_ring(const char* name, uint64_t capacity, int create) {
  uint64_t map_len = sizeof(Header) + capacity;
  int flags = create ? (O_RDWR | O_CREAT | O_EXCL) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  if (create && ftruncate(fd, (off_t)map_len) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  if (!create) {
    struct stat st;
    if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    map_len = (uint64_t)st.st_size;
  }
  void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Ring* r = new Ring();
  r->hdr = (Header*)mem;
  r->data = (uint8_t*)mem + sizeof(Header);
  r->map_len = map_len;
  r->owner = create;
  snprintf(r->name, sizeof(r->name), "%s", name);
  if (create) {
    r->hdr->head.store(0);
    r->hdr->tail.store(0);
    r->hdr->closed.store(0);
    r->hdr->capacity = capacity;
  }
  return r;
}

}  // namespace

extern "C" {

void* ring_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed run
  return open_ring(name, capacity, 1);
}

void* ring_attach(const char* name) { return open_ring(name, 0, 0); }

// 0 ok; -1 timeout; -2 message larger than capacity; -3 closed
int ring_push(void* rv, const void* buf, uint64_t len, int timeout_ms) {
  Ring* r = (Ring*)rv;
  uint64_t need = len + 8;
  uint64_t cap = r->hdr->capacity;
  if (need > cap) return -2;
  uint64_t start = now_ms();
  unsigned spins = 0;
  for (;;) {
    uint64_t head = r->hdr->head.load(std::memory_order_acquire);
    uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
    if (cap - (tail - head) >= need) {
      uint64_t le = len;
      put_bytes(r, tail, &le, 8);
      put_bytes(r, tail + 8, buf, len);
      r->hdr->tail.store(tail + need, std::memory_order_release);
      return 0;
    }
    if (r->hdr->closed.load(std::memory_order_relaxed)) return -3;
    if (timeout_ms >= 0 && now_ms() - start > (uint64_t)timeout_ms) return -1;
    backoff(spins);
  }
}

// >=0: message length copied; -1 timeout; -2 out buffer too small (length
// returned via *need_out, message left in place); -3 closed and drained
long ring_pop(void* rv, void* out, uint64_t out_cap, int timeout_ms,
              uint64_t* need_out) {
  Ring* r = (Ring*)rv;
  uint64_t start = now_ms();
  unsigned spins = 0;
  for (;;) {
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
    if (tail - head >= 8) {
      uint64_t len;
      get_bytes(r, head, &len, 8);
      if (len > out_cap) {
        if (need_out) *need_out = len;
        return -2;
      }
      get_bytes(r, head + 8, out, len);
      r->hdr->head.store(head + 8 + len, std::memory_order_release);
      return (long)len;
    }
    if (r->hdr->closed.load(std::memory_order_relaxed)) return -3;
    if (timeout_ms >= 0 && now_ms() - start > (uint64_t)timeout_ms) return -1;
    backoff(spins);
  }
}

// peek the next message length without consuming (-1 if empty)
long ring_next_len(void* rv) {
  Ring* r = (Ring*)rv;
  uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  if (tail - head < 8) return -1;
  uint64_t len;
  get_bytes(r, head, &len, 8);
  return (long)len;
}

void ring_close_producer(void* rv) {
  ((Ring*)rv)->hdr->closed.store(1, std::memory_order_release);
}

uint64_t ring_size(void* rv) {
  Ring* r = (Ring*)rv;
  return r->hdr->tail.load() - r->hdr->head.load();
}

void ring_free(void* rv, int unlink) {
  Ring* r = (Ring*)rv;
  munmap((void*)r->hdr, r->map_len);
  if (unlink) shm_unlink(r->name);
  delete r;
}

}  // extern "C"
