"""DataLoader (reference: `python/paddle/io/reader.py:216`).

Multiprocess workers + prefetch: worker processes produce numpy batches over a
`multiprocessing` queue (the reference's shared-mem mmap allocator path); the main
process converts to device Tensors.  num_workers=0 runs synchronously in-process, like
the reference.  A background prefetch thread keeps `prefetch_factor` batches in flight
so host→HBM transfer overlaps step compute (AsyncLoader parity).
"""
from __future__ import annotations

import itertools
import queue as _queue
import threading
from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

_worker_info = None


def get_worker_info():
    return _worker_info


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._data) for s in batch])
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn([b[i] for b in batch]) for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return np.asarray(batch)


def _to_tensors(batch, places=None):
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    if isinstance(batch, (list, tuple)):
        return [_to_tensors(b, places) for b in batch]
    if isinstance(batch, dict):
        return {k: _to_tensors(v, places) for k, v in batch.items()}
    if isinstance(batch, Tensor):
        return batch
    return Tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.places = places
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable_ds = isinstance(dataset, IterableDataset)
        if self._iterable_ds:
            self.batch_size = batch_size
            self.batch_sampler = None
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                                  batch_size=batch_size,
                                                  drop_last=drop_last)
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable_ds:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # ---- single-process iteration ----
    def _iter_sync(self):
        if self._iterable_ds:
            global _worker_info
            _worker_info = WorkerInfo(0, 1, self.dataset)
            if self.worker_init_fn:
                self.worker_init_fn(0)
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield _to_tensors(self.collate_fn(batch), self.places)
        elif self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield _to_tensors(self.dataset[i], self.places)
        else:
            for indices in self.batch_sampler:
                batch = [self.dataset[i] for i in indices]
                yield _to_tensors(self.collate_fn(batch), self.places)

    # ---- threaded prefetch (overlap host work with device compute) ----
    def _iter_prefetch(self):
        q: _queue.Queue = _queue.Queue(maxsize=self.prefetch_factor * max(self.num_workers, 1))
        sentinel = object()
        err = []

        def producer():
            try:
                if self._iterable_ds:
                    for item in self._iter_sync():
                        q.put(item)
                else:
                    for indices in self.batch_sampler:
                        batch = [self.dataset[i] for i in indices]
                        q.put(_to_tensors(self.collate_fn(batch), self.places))
            except BaseException as e:  # surface worker errors in main thread
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        if err:
            raise err[0]

    def __iter__(self):
        if self.num_workers == 0:
            return self._iter_sync()
        return self._iter_prefetch()
