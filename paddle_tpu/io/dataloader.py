"""DataLoader (reference: `python/paddle/io/reader.py:216`).

num_workers>0 with use_shared_memory=True forks real worker PROCESSES that
push collated batches through a native C++ shared-memory ring per worker
(`io/csrc/shm_ring.cc` — the reference's mmap_allocator + C++ blocking-queue
path); the main process pops in round-robin order and converts to device
Tensors.  Without shared memory (or if the toolchain is unavailable, or the
dataset doesn't pickle) a prefetch thread keeps `prefetch_factor` batches in
flight.  num_workers=0 runs synchronously in-process, like the reference.

Workers are SPAWNED (JAX's XLA runtime is not fork-safe), so like the
reference on spawn platforms, scripts using num_workers>0 must guard their
entry point with `if __name__ == "__main__":`.
"""
from __future__ import annotations

import itertools
import multiprocessing as _mp
import os
import queue as _queue
import threading
import traceback
from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

_worker_info = None


def get_worker_info():
    return _worker_info


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._data) for s in batch])
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn([b[i] for b in batch]) for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return np.asarray(batch)


def _to_tensors(batch, places=None):
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    if isinstance(batch, (list, tuple)):
        return [_to_tensors(b, places) for b in batch]
    if isinstance(batch, dict):
        return {k: _to_tensors(v, places) for k, v in batch.items()}
    if isinstance(batch, Tensor):
        return batch
    return Tensor(np.asarray(batch))


def _mp_worker_main(wid, num_workers, dataset, collate_fn, worker_init_fn,
                    ring_name, assigned):
    """Spawned worker entry: build assigned batches, push through the shm ring.

    Module-level (not a bound method) so only these picklable fields cross the
    spawn boundary — an unpicklable places/batch_sampler on the DataLoader
    itself must not reach Process.start()."""
    from .shm_ring import ShmRing
    global _worker_info
    _worker_info = WorkerInfo(wid, num_workers, dataset)
    ring = None
    try:
        ring = ShmRing(ring_name, create=False)
        if worker_init_fn:
            worker_init_fn(wid)
        for indices in assigned:
            batch = [dataset[i] for i in indices]
            ring.put(collate_fn(batch))
    except BaseException:
        if ring is not None:
            try:
                ring.put({"__dataloader_worker_error__":
                          traceback.format_exc()})
            except Exception:
                pass
    finally:
        if ring is not None:
            ring.close_producer()
        os._exit(0)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.places = places
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        self._iterable_ds = isinstance(dataset, IterableDataset)
        if self._iterable_ds:
            self.batch_size = batch_size
            self.batch_sampler = None
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                                  batch_size=batch_size,
                                                  drop_last=drop_last)
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable_ds:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # ---- single-process iteration ----
    def _iter_sync(self):
        if self._iterable_ds:
            global _worker_info
            _worker_info = WorkerInfo(0, 1, self.dataset)
            if self.worker_init_fn:
                self.worker_init_fn(0)
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield _to_tensors(self.collate_fn(batch), self.places)
        elif self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield _to_tensors(self.dataset[i], self.places)
        else:
            for indices in self.batch_sampler:
                batch = [self.dataset[i] for i in indices]
                yield _to_tensors(self.collate_fn(batch), self.places)

    # ---- threaded prefetch (overlap host work with device compute) ----
    def _iter_prefetch(self):
        q: _queue.Queue = _queue.Queue(maxsize=self.prefetch_factor * max(self.num_workers, 1))
        sentinel = object()
        err = []

        def producer():
            try:
                if self._iterable_ds or self.batch_sampler is None:
                    for item in self._iter_sync():
                        q.put(item)
                else:
                    for indices in self.batch_sampler:
                        batch = [self.dataset[i] for i in indices]
                        q.put(_to_tensors(self.collate_fn(batch), self.places))
            except BaseException as e:  # surface worker errors in main thread
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        if err:
            raise err[0]

    _ring_counter = itertools.count()

    def _iter_multiprocess(self):
        from .shm_ring import TIMEOUT, ShmRing
        nw = self.num_workers
        batches = list(self.batch_sampler)
        cap = max(16 << 20, (self.prefetch_factor or 2) * 8 << 20)
        # unique per iterator: concurrent iterators/loaders must not collide
        # (ring_create clobbers an existing segment of the same name)
        tag = f"pt_dl_{os.getpid()}_{next(DataLoader._ring_counter)}"
        rings = [ShmRing(f"{tag}_{w}", capacity=cap) for w in range(nw)]
        # spawn, not fork: the parent's XLA runtime is live and JAX is not
        # fork-safe; spawned children import fresh (dataset must pickle —
        # __iter__ pre-checks and falls back to the threaded path otherwise)
        ctx = _mp.get_context("spawn")
        procs = []
        try:
            for w in range(nw):
                assigned = batches[w::nw]
                p = ctx.Process(target=_mp_worker_main,
                                args=(w, nw, self.dataset, self.collate_fn,
                                      self.worker_init_fn, rings[w].name,
                                      assigned),
                                daemon=True)
                p.start()
                procs.append(p)
            timeout_ms = int(self.timeout * 1000) if self.timeout else -1
            for i in range(len(batches)):
                ring = rings[i % nw]
                proc = procs[i % nw]
                while True:
                    # bounded poll so a dead worker (OOM-kill, attach failure)
                    # surfaces as an error instead of an infinite hang
                    obj = ring.get(timeout_ms=1000 if timeout_ms < 0
                                   else min(1000, timeout_ms))
                    if obj is not TIMEOUT:
                        break
                    if not proc.is_alive() and ring.size() == 0:
                        raise RuntimeError(
                            f"DataLoader worker {i % nw} died "
                            f"(exitcode={proc.exitcode})")
                    if timeout_ms >= 0:
                        timeout_ms -= 1000
                        if timeout_ms <= 0:
                            raise TimeoutError(
                                f"DataLoader worker {i % nw} timed out after "
                                f"{self.timeout}s")
                if isinstance(obj, dict) and "__dataloader_worker_error__" in obj:
                    raise RuntimeError("DataLoader worker failed:\n"
                                       + obj["__dataloader_worker_error__"])
                yield _to_tensors(obj, self.places)
        finally:
            for p in procs:
                p.terminate()
                p.join(timeout=5)
            for r in rings:
                r.free()

    def _picklable_for_workers(self):
        # must mirror the exact _mp_worker_main payload: nothing else of the
        # DataLoader crosses the spawn boundary
        import pickle as _pickle
        try:
            _pickle.dumps((self.dataset, self.collate_fn,
                           self.worker_init_fn))
            return True
        except Exception:
            return False

    def __iter__(self):
        if self.num_workers == 0:
            return self._iter_sync()
        if self.use_shared_memory and not self._iterable_ds \
                and self.batch_sampler is not None:
            from .shm_ring import available
            if available() and self._picklable_for_workers():
                return self._iter_multiprocess()
        return self._iter_prefetch()
