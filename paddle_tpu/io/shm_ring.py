"""Python wrapper over the C++ shared-memory ring (io/csrc/shm_ring.cc).

One SPSC ring per DataLoader worker: the worker process pushes serialized
batches, the main process pops them — large batch payloads move through POSIX
shared memory with two memcpys and no pickling through a multiprocessing pipe
(ref mmap_allocator + blocking_queue design).
"""
from __future__ import annotations

import ctypes
import os
import pickle
from typing import Optional

_LIB = None
_LIB_ERR = None


def _lib():
    global _LIB, _LIB_ERR
    if _LIB is not None or _LIB_ERR is not None:
        return _LIB
    try:
        from ..utils.cpp_extension import load
        src = os.path.join(os.path.dirname(__file__), "csrc", "shm_ring.cc")
        lib = load("shm_ring", [src])
        lib.ring_create.restype = ctypes.c_void_p
        lib.ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.ring_attach.restype = ctypes.c_void_p
        lib.ring_attach.argtypes = [ctypes.c_char_p]
        lib.ring_push.restype = ctypes.c_int
        lib.ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint64, ctypes.c_int]
        lib.ring_pop.restype = ctypes.c_long
        lib.ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_uint64, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_uint64)]
        lib.ring_next_len.restype = ctypes.c_long
        lib.ring_next_len.argtypes = [ctypes.c_void_p]
        lib.ring_close_producer.argtypes = [ctypes.c_void_p]
        lib.ring_size.restype = ctypes.c_uint64
        lib.ring_size.argtypes = [ctypes.c_void_p]
        lib.ring_free.argtypes = [ctypes.c_void_p, ctypes.c_int]
        _LIB = lib
    except Exception as e:  # toolchain or /dev/shm unavailable
        _LIB_ERR = e
        _LIB = None
    return _LIB


def available() -> bool:
    return _lib() is not None


class _Timeout:
    def __repr__(self):
        return "<shm_ring.TIMEOUT>"


TIMEOUT = _Timeout()  # distinct from a legitimately transferred None


class ShmRing:
    """SPSC byte ring over POSIX shared memory."""

    def __init__(self, name: str, capacity: int = 64 << 20, create: bool = True,
                 unlink_on_free: Optional[bool] = None):
        lib = _lib()
        if lib is None:
            raise RuntimeError(f"shm_ring unavailable: {_LIB_ERR}")
        self._lib = lib
        self.name = name if name.startswith("/") else "/" + name
        bname = self.name.encode()
        self._h = lib.ring_create(bname, capacity) if create \
            else lib.ring_attach(bname)
        if not self._h:
            raise RuntimeError(f"shm ring {'create' if create else 'attach'} "
                               f"failed for {self.name}")
        self._unlink = create if unlink_on_free is None else unlink_on_free
        self._buf = ctypes.create_string_buffer(1 << 20)

    # ---- raw bytes ----
    def push_bytes(self, data: bytes, timeout_ms: int = -1) -> bool:
        rc = self._lib.ring_push(self._h, data, len(data), timeout_ms)
        if rc == -2:
            raise ValueError(f"message of {len(data)} bytes exceeds ring "
                             "capacity")
        if rc == -3:
            raise BrokenPipeError("ring closed")
        return rc == 0

    def pop_bytes(self, timeout_ms: int = -1) -> Optional[bytes]:
        need = ctypes.c_uint64(0)
        while True:
            n = self._lib.ring_pop(self._h, self._buf, len(self._buf),
                                   timeout_ms, ctypes.byref(need))
            if n >= 0:
                return self._buf.raw[:n]
            if n == -1:
                return None                      # timeout
            if n == -3:
                raise EOFError("ring closed and drained")
            # -2: grow the scratch buffer and retry
            self._buf = ctypes.create_string_buffer(int(need.value))

    # ---- pickled objects ----
    def put(self, obj, timeout_ms: int = -1) -> bool:
        return self.push_bytes(pickle.dumps(obj, protocol=4), timeout_ms)

    def get(self, timeout_ms: int = -1):
        """Returns the object, or the TIMEOUT sentinel on pop timeout (a
        transferred None comes back as None)."""
        data = self.pop_bytes(timeout_ms)
        return TIMEOUT if data is None else pickle.loads(data)

    def close_producer(self):
        self._lib.ring_close_producer(self._h)

    def size(self) -> int:
        return int(self._lib.ring_size(self._h))

    def free(self):
        if self._h:
            self._lib.ring_free(self._h, 1 if self._unlink else 0)
            self._h = None

    def __del__(self):
        try:
            self.free()
        except Exception:
            pass
