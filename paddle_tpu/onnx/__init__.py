"""paddle.onnx — export surface.

Scope decision (recorded per VERDICT round-1 item 10): the reference's
`paddle.onnx.export` delegates to the external paddle2onnx package, which
converts ProgramDesc protobufs — an IR this framework intentionally does not
have.  The TPU-native serialized program format is StableHLO (via
`paddle.jit.save` / `paddle.static.save_inference_model`), which is the
portable interchange format of the XLA ecosystem and is what TPU serving
stacks consume.  ONNX interchange, if needed, should go StableHLO -> ONNX via
community converters outside this framework.
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """ref onnx/export.py — see module docstring for the scope decision."""
    raise NotImplementedError(
        "paddle.onnx.export is descoped on TPU: the deployment format is "
        "StableHLO — use paddle.jit.save(layer, path, input_spec) and serve "
        "the .pdmodel with paddle.inference.Predictor; convert StableHLO to "
        "ONNX externally if interchange is required")


__all__ = ["export"]
