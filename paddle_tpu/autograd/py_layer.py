"""PyLayer: user-defined autograd functions.

Reference parity: `paddle.autograd.PyLayer` (`paddle/fluid/eager/pylayer/`,
`fluid/pybind/eager_py_layer.cc`).  The user supplies `forward(ctx, ...)` and
`backward(ctx, *out_grads)` static methods; apply() records a GradNode whose pullback
invokes the user's backward.
"""
from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp

from ..core import autograd as _ag
from ..core.tensor import Tensor

_saved_hooks: List = []


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._materialize_grads = True
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        if _saved_hooks:
            pack, _ = _saved_hooks[-1]
            self._saved = tuple(pack(t) for t in tensors)
            self._packed = True
        else:
            self._saved = tensors
            self._packed = False

    def saved_tensor(self):
        if getattr(self, "_packed", False):
            _, unpack = _saved_hooks[-1] if _saved_hooks else (None, lambda x: x)
            return tuple(unpack(t) for t in self._saved)
        return self._saved

    saved_tensors = property(lambda self: self.saved_tensor())

    def mark_not_inplace(self, *tensors):
        self.not_inplace_tensors = tensors

    def set_materialize_grads(self, value: bool):
        self._materialize_grads = bool(value)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with _ag.set_grad_enabled(False):
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        need_grad = _ag.is_grad_enabled() and any(not t.stop_gradient for t in tensor_inputs)
        if not need_grad:
            return outs

        n_out = len([o for o in out_list if isinstance(o, Tensor)])

        def vjp_fn(cots):
            if n_out == 1 or not isinstance(cots, tuple):
                cots = (cots,)
            grad_in = [Tensor(c, stop_gradient=True) for c in cots]
            with _ag.set_grad_enabled(False):
                gins = cls.backward(ctx, *grad_in)
            if not isinstance(gins, (tuple, list)):
                gins = (gins,)
            # map returned grads back to positional tensor inputs
            out = []
            gi = iter(gins)
            for a in args:
                if isinstance(a, Tensor):
                    g = next(gi, None)
                    out.append(None if g is None else (g._data if isinstance(g, Tensor) else jnp.asarray(g)))
                else:
                    out.append(None)
            return tuple(out)

        specs = [(tuple(o._data.shape), o._data.dtype) for o in out_list if isinstance(o, Tensor)]
        node = _ag.GradNode(cls.__name__, vjp_fn, list(args), n_out, specs)
        idx = 0
        for o in out_list:
            if isinstance(o, Tensor) and jnp.issubdtype(o._data.dtype, jnp.inexact):
                o.stop_gradient = False
                o._grad_node = node
                o._out_index = idx
                idx += 1
            elif isinstance(o, Tensor):
                idx += 1
        return outs


LegacyPyLayer = PyLayer
