"""paddle.autograd parity: grad, backward, PyLayer, hooks."""
from ..core.autograd import grad, no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa
from ..core import autograd as _ag
from .py_layer import PyLayer, PyLayerContext  # noqa


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward."""
    _ag.run_backward(tensors, grad_tensors, retain_graph)


class saved_tensors_hooks:
    """API-compat context (`paddle.autograd.saved_tensors_hooks`): registers pack/unpack
    hooks for tensors saved for backward.  The tape stores pullback closures rather than
    tensors, so hooks apply to PyLayer saved tensors only."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from . import py_layer
        py_layer._saved_hooks.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        from . import py_layer
        py_layer._saved_hooks.pop()
        return False


def jacobian(ys, xs, batch_axis=None):
    """ref autograd/autograd.py jacobian: lazy full Jacobian of ys w.r.t. xs.

    TPU-native: delegates to jax.jacobian over the recorded forward (xs must be
    leaves; computed eagerly, returned as a Tensor [*ys.shape, *xs.shape])."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    if callable(ys):
        fn, at = ys, xs
        data = at._data if isinstance(at, Tensor) else jnp.asarray(at)
        jac = jax.jacobian(lambda a: fn(Tensor(a, stop_gradient=False))._data)(data)
        return Tensor(jac)
    # tensor form: differentiate by replaying grads per output element
    out = []
    flat = ys.reshape([-1])
    for i in range(int(flat.size)):
        g = grad(flat[i], xs, retain_graph=True, create_graph=False,
                 allow_unused=True)
        out.append(g[0] if isinstance(g, (list, tuple)) else g)
    import numpy as np
    stacked = jnp.stack([o._data if o is not None else jnp.zeros_like(xs._data)
                         for o in out])
    return Tensor(stacked.reshape(tuple(ys.shape) + tuple(xs.shape)))


def hessian(func, xs, batch_axis=None):
    """ref autograd/autograd.py hessian (function form)."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    data = xs._data if isinstance(xs, Tensor) else jnp.asarray(xs)
    h = jax.hessian(lambda a: func(Tensor(a, stop_gradient=False))._data.sum())(data)
    return Tensor(h)
