"""paddle.autograd parity: grad, backward, PyLayer, hooks."""
from ..core.autograd import grad, no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa
from ..core import autograd as _ag
from .py_layer import PyLayer, PyLayerContext  # noqa


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward."""
    _ag.run_backward(tensors, grad_tensors, retain_graph)


class saved_tensors_hooks:
    """API-compat context (`paddle.autograd.saved_tensors_hooks`): registers pack/unpack
    hooks for tensors saved for backward.  The tape stores pullback closures rather than
    tensors, so hooks apply to PyLayer saved tensors only."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from . import py_layer
        py_layer._saved_hooks.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        from . import py_layer
        py_layer._saved_hooks.pop()
        return False
