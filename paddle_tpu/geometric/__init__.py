"""paddle.geometric — graph message passing + sampling.

Reference parity: `python/paddle/geometric/` (send_u_recv/send_ue_recv/send_uv
over `graph_send_recv`/`graph_send_ue_recv` kernels, segment ops, neighbor
sampling + reindexing).

TPU-native: message passing lowers to XLA segment reductions (one fused
scatter each); sampling/reindex are host-side numpy (dynamic shapes are
host-side in the reference too — the GPU kernels there serve its GPU PS
pipeline, which is descoped; see README).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply, _to_data
from ..incubate.segment_ops import (segment_max, segment_mean, segment_min,
                                    segment_sum)

__all__ = ["reindex_graph", "reindex_heter_graph", "sample_neighbors",
           "segment_max", "segment_mean", "segment_min", "segment_sum",
           "send_u_recv", "send_ue_recv", "send_uv",
           "weighted_sample_neighbors"]

_RED = {"sum": jax.ops.segment_sum, "mean": None, "max": jax.ops.segment_max,
        "min": jax.ops.segment_min}

_OPS = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
        "div": jnp.divide}


def _reduce(msgs, dst, n, pool):
    dst32 = dst.astype(jnp.int32)
    if pool == "mean":
        s = jax.ops.segment_sum(msgs, dst32, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), msgs.dtype),
                                  dst32, num_segments=n)
        return s / jnp.maximum(cnt, 1.0).reshape(
            (-1,) + (1,) * (msgs.ndim - 1))
    out = _RED[pool](msgs, dst32, num_segments=n)
    if pool in ("max", "min"):
        # reference zero-fills nodes that receive no message (not +/-inf)
        cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), jnp.float32),
                                  dst32, num_segments=n)
        mask = (cnt > 0).reshape((-1,) + (1,) * (msgs.ndim - 1))
        out = jnp.where(mask, out, 0.0).astype(msgs.dtype)
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src], reduce into dst (ref send_u_recv / graph_send_recv)."""
    def f(a, si, di):
        n = out_size or a.shape[0]
        return _reduce(a[si.astype(jnp.int32)], di, n, reduce_op)
    return apply("send_u_recv", f, x, src_index, dst_index)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features x[src] with edge features y, reduce into dst
    (ref send_ue_recv / graph_send_ue_recv)."""
    mop = _OPS[message_op]

    def f(a, e, si, di):
        msgs = mop(a[si.astype(jnp.int32)], e)
        n = out_size or a.shape[0]
        return _reduce(msgs, di, n, reduce_op)
    return apply("send_ue_recv", f, x, y, src_index, dst_index)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints (ref send_uv / graph_send_uv)."""
    mop = _OPS[message_op]

    def f(a, b, si, di):
        return mop(a[si.astype(jnp.int32)], b[di.astype(jnp.int32)])
    return apply("send_uv", f, x, y, src_index, dst_index)


# fresh stream per process: every sample_neighbors call must draw different
# neighborhoods (GraphSAGE-style training resamples each minibatch)
_sample_rng = np.random.RandomState()


def _sample(row, colptr, input_nodes, sample_size, eids, return_eids,
            weight=None):
    rown = np.asarray(_to_data(row)).astype(np.int64)
    cptr = np.asarray(_to_data(colptr)).astype(np.int64)
    nodes = np.asarray(_to_data(input_nodes)).astype(np.int64).reshape(-1)
    w = None if weight is None else \
        np.asarray(_to_data(weight)).astype(np.float64).reshape(-1)
    ed = np.arange(len(rown), dtype=np.int64) if eids is None \
        else np.asarray(_to_data(eids)).astype(np.int64).reshape(-1)
    out_rows, out_eids, out_count = [], [], []
    for v in nodes:
        beg, end = cptr[v], cptr[v + 1]
        idx = np.arange(beg, end)
        if sample_size >= 0 and len(idx) > sample_size:
            p = None if w is None else w[idx] / w[idx].sum()
            idx = _sample_rng.choice(idx, size=sample_size, replace=False, p=p)
        out_rows.append(rown[idx])
        out_eids.append(ed[idx])
        out_count.append(len(idx))
    cat = lambda xs: (np.concatenate(xs) if xs else np.zeros(0, np.int64))  # noqa: E731
    res = (Tensor(jnp.asarray(cat(out_rows))),
           Tensor(jnp.asarray(np.asarray(out_count, np.int64))))
    if return_eids:
        return res + (Tensor(jnp.asarray(cat(out_eids))),)
    return res


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling from CSC (ref sample_neighbors) — host-side."""
    return _sample(row, colptr, input_nodes, sample_size, eids, return_eids)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-proportional sampling (ref weighted_sample_neighbors)."""
    return _sample(row, colptr, input_nodes, sample_size, eids, return_eids,
                   weight=edge_weight)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Renumber a sampled subgraph to contiguous ids (ref reindex_graph)."""
    xs = np.asarray(_to_data(x)).astype(np.int64).reshape(-1)
    neigh = np.asarray(_to_data(neighbors)).astype(np.int64).reshape(-1)
    cnt = np.asarray(_to_data(count)).astype(np.int64).reshape(-1)
    # order: input nodes first, then unseen neighbors in appearance order
    seen = {int(v): i for i, v in enumerate(xs)}
    nodes = list(xs)
    for v in neigh:
        if int(v) not in seen:
            seen[int(v)] = len(nodes)
            nodes.append(int(v))
    reindex_src = np.asarray([seen[int(v)] for v in neigh], np.int64)
    reindex_dst = np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(np.asarray(nodes, np.int64))))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: neighbors/count are per-edge-type lists, all
    keyed by the SAME input nodes x; discovered nodes share one id space."""
    xs = np.asarray(_to_data(x)).astype(np.int64).reshape(-1)
    seen = {int(v): i for i, v in enumerate(xs)}
    nodes = list(xs)
    srcs, dsts = [], []
    for n_i, c_i in zip(neighbors, count):
        neigh = np.asarray(_to_data(n_i)).astype(np.int64).reshape(-1)
        cnt = np.asarray(_to_data(c_i)).astype(np.int64).reshape(-1)
        for v in neigh:
            if int(v) not in seen:
                seen[int(v)] = len(nodes)
                nodes.append(int(v))
        srcs.append(Tensor(jnp.asarray(
            np.asarray([seen[int(v)] for v in neigh], np.int64))))
        dsts.append(Tensor(jnp.asarray(
            np.repeat(np.arange(len(xs), dtype=np.int64), cnt))))
    return srcs, dsts, Tensor(jnp.asarray(np.asarray(nodes, np.int64)))
