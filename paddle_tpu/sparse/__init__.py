"""paddle.sparse — COO/CSR sparse tensors and ops.

Reference parity: `python/paddle/sparse/` (creation.py, unary.py, binary.py,
multiary.py; kernels `phi/kernels/sparse/`).

TPU-native design: a sparse tensor is (structure metadata + a dense values
Tensor).  Values participate in the eager autograd tape like any Tensor, so
gradients flow through sparse ops to the values.  Elementwise ops act on values
and preserve structure; matmul/masked_matmul lower to XLA scatter/gather +
dense MXU matmuls — on TPU, dense-masked compute at the sparsity levels this
API targets beats gather-based kernels, which is the same call the reference
makes on GPU by routing through cuSPARSE only above fixed density thresholds.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply, _to_data

__all__ = [
    'abs', 'add', 'addmm', 'asin', 'asinh', 'atan', 'atanh', 'cast', 'coalesce',
    'deg2rad', 'divide', 'expm1', 'is_same_shape', 'isnan', 'log1p',
    'masked_matmul', 'matmul', 'multiply', 'mv', 'neg', 'pca_lowrank', 'pow',
    'rad2deg', 'reshape', 'sin', 'sinh', 'slice', 'sparse_coo_tensor',
    'sparse_csr_tensor', 'sqrt', 'square', 'subtract', 'sum', 'tan', 'tanh',
    'transpose', 'SparseCooTensor', 'SparseCsrTensor',
]


class SparseCooTensor:
    """COO sparse tensor: indices [sparse_dim, nnz] + values Tensor [nnz, ...]."""

    def __init__(self, indices, values, shape, coalesced=False):
        self._indices = jnp.asarray(_to_data(indices), jnp.int64) \
            if not isinstance(indices, jnp.ndarray) else indices.astype(jnp.int64)
        self._values = values if isinstance(values, Tensor) else Tensor(_to_data(values))
        self._shape = tuple(int(s) for s in shape)
        self._coalesced = coalesced

    # -- paddle Tensor-like surface --
    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values._data.dtype

    @property
    def stop_gradient(self):
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._values.stop_gradient = v

    def indices(self):
        return Tensor(self._indices)

    def values(self):
        return self._values

    def nnz(self):
        return int(self._indices.shape[1])

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def is_sparse(self):
        return True

    def to_dense(self):
        idx = self._indices
        shape = self._shape
        sd = idx.shape[0]

        def f(v):
            out = jnp.zeros(shape, v.dtype)
            return out.at[tuple(idx[i] for i in range(sd))].add(v)
        return apply("sparse_to_dense", f, self._values)

    def to_sparse_csr(self):
        assert len(self._shape) == 2, "to_sparse_csr expects a 2-D COO tensor"
        coo = coalesce(self)
        rows = np.asarray(coo._indices[0])
        cols = np.asarray(coo._indices[1])
        order = np.lexsort((cols, rows))
        crows = np.zeros(self._shape[0] + 1, np.int64)
        np.add.at(crows, rows[order] + 1, 1)
        crows = np.cumsum(crows)
        vals = apply("csr_reorder", lambda v: v[jnp.asarray(order)], coo._values)
        return SparseCsrTensor(crows, cols[order], vals, self._shape)

    def numpy(self):
        return np.asarray(self.to_dense()._data)

    def backward(self, *a, **kw):
        return self._values.backward(*a, **kw)

    @property
    def grad(self):
        return self._values.grad

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor: crows [rows+1], cols [nnz], values Tensor [nnz]."""

    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(_to_data(crows), jnp.int64)
        self._cols = jnp.asarray(_to_data(cols), jnp.int64)
        self._values = values if isinstance(values, Tensor) else Tensor(_to_data(values))
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values._data.dtype

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def values(self):
        return self._values

    def nnz(self):
        return int(self._cols.shape[0])

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def is_sparse(self):
        return True

    def _row_ids(self):
        nnz = self._cols.shape[0]
        j = jnp.arange(nnz)
        return jnp.sum(j[None, :] >= self._crows[1:, None], axis=0)

    def to_sparse_coo(self, sparse_dim=2):
        rows = self._row_ids()
        return SparseCooTensor(jnp.stack([rows, self._cols]), self._values,
                               self._shape, coalesced=True)

    def to_dense(self):
        rows = self._row_ids()
        cols = self._cols
        shape = self._shape

        def f(v):
            out = jnp.zeros(shape, v.dtype)
            return out.at[rows, cols].add(v)
        return apply("csr_to_dense", f, self._values)

    def numpy(self):
        return np.asarray(self.to_dense()._data)

    def backward(self, *a, **kw):
        return self._values.backward(*a, **kw)

    @property
    def grad(self):
        return self._values.grad

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self._shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def _is_sparse(x):
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


# ---------------------------------------------------------------------------
# creation (ref sparse/creation.py)
# ---------------------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    idx = jnp.asarray(_to_data(indices), jnp.int64)
    vals = values if isinstance(values, Tensor) else Tensor(_to_data(values))
    if dtype is not None:
        from ..core.dtype import to_np
        vals = Tensor(vals._data.astype(to_np(dtype)))
    if shape is None:
        dense_dims = tuple(vals._data.shape[1:])
        shape = tuple(int(m) + 1 for m in np.asarray(idx.max(axis=1))) + dense_dims
    vals.stop_gradient = stop_gradient
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vals = values if isinstance(values, Tensor) else Tensor(_to_data(values))
    if dtype is not None:
        from ..core.dtype import to_np
        vals = Tensor(vals._data.astype(to_np(dtype)))
    vals.stop_gradient = stop_gradient
    return SparseCsrTensor(crows, cols, vals, shape)


def coalesce(x, name=None):
    """Merge duplicate COO indices (ref sparse_coalesce)."""
    assert isinstance(x, SparseCooTensor)
    idx = np.asarray(x._indices)
    flat = np.ravel_multi_index(tuple(idx), x._shape[:idx.shape[0]])
    uniq, inv = np.unique(flat, return_inverse=True)
    new_idx = jnp.asarray(np.stack(np.unravel_index(uniq, x._shape[:idx.shape[0]])),
                          jnp.int64)
    inv_j = jnp.asarray(inv)
    n_out = int(uniq.shape[0])
    vals = apply("sparse_coalesce",
                 lambda v: jax.ops.segment_sum(v, inv_j, num_segments=n_out),
                 x._values)
    return SparseCooTensor(new_idx, vals, x._shape, coalesced=True)


# ---------------------------------------------------------------------------
# unary (ref sparse/unary.py — act on explicit values, structure preserved)
# ---------------------------------------------------------------------------

def _unary(name, jfn):
    def op(x, name=None):
        if not _is_sparse(x):
            return apply(name_, jfn, x)
        vals = apply(name_, jfn, x._values)
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)
        return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
    name_ = name
    op.__name__ = name
    return op


abs = _unary("sparse_abs", jnp.abs)
asin = _unary("sparse_asin", jnp.arcsin)
asinh = _unary("sparse_asinh", jnp.arcsinh)
atan = _unary("sparse_atan", jnp.arctan)
atanh = _unary("sparse_atanh", jnp.arctanh)
expm1 = _unary("sparse_expm1", jnp.expm1)
log1p = _unary("sparse_log1p", jnp.log1p)
neg = _unary("sparse_neg", jnp.negative)
sin = _unary("sparse_sin", jnp.sin)
sinh = _unary("sparse_sinh", jnp.sinh)
sqrt = _unary("sparse_sqrt", jnp.sqrt)
square = _unary("sparse_square", jnp.square)
tan = _unary("sparse_tan", jnp.tan)
tanh = _unary("sparse_tanh", jnp.tanh)
deg2rad = _unary("sparse_deg2rad", jnp.deg2rad)
rad2deg = _unary("sparse_rad2deg", jnp.rad2deg)
isnan = _unary("sparse_isnan", jnp.isnan)


def pow(x, factor, name=None):
    return _unary("sparse_pow", lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..core.dtype import to_np
    vals = x._values if _is_sparse(x) else x
    if value_dtype is not None:
        vals = apply("sparse_cast", lambda v: v.astype(to_np(value_dtype)), vals)
    if isinstance(x, SparseCooTensor):
        idx = x._indices.astype(to_np(index_dtype)) if index_dtype else x._indices
        return SparseCooTensor(idx, vals, x._shape, x._coalesced)
    if isinstance(x, SparseCsrTensor):
        if index_dtype:
            return SparseCsrTensor(x._crows.astype(to_np(index_dtype)),
                                   x._cols.astype(to_np(index_dtype)), vals, x._shape)
        return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
    return vals


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """ref sparse sum: reduces over all or one axis; returns dense Tensor for
    full reduction, sparse otherwise (we return dense for simplicity of axis
    reductions too — the reference's axis support is also dense-shaped)."""
    d = x.to_dense() if _is_sparse(x) else x
    from ..ops.math import sum as dense_sum
    return dense_sum(d, axis=axis, keepdim=keepdim)


def slice(x, axes, starts, ends, name=None):
    d = x.to_dense() if _is_sparse(x) else x

    def f(a):
        sl = [np.s_[:]] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            sl[ax] = np.s_[s:e]
        return a[tuple(sl)]
    dense = apply("sparse_slice", f, d)
    return _dense_to_coo(dense)


def reshape(x, shape, name=None):
    dense = x.to_dense() if _is_sparse(x) else x
    from ..ops.manipulation import reshape as dreshape
    out = dreshape(dense, shape)
    return _dense_to_coo(out) if isinstance(x, SparseCooTensor) else out


def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor) and len(perm) == x._indices.shape[0]:
        new_idx = x._indices[jnp.asarray(perm)]
        new_shape = tuple(x._shape[p] for p in perm)
        return SparseCooTensor(new_idx, x._values, new_shape)
    from ..ops.manipulation import transpose as dtranspose
    out = dtranspose(x.to_dense() if _is_sparse(x) else x, perm)
    return _dense_to_coo(out) if _is_sparse(x) else out


def _dense_to_coo(dense, sparse_dim=None):
    d = np.asarray(dense._data)
    sd = sparse_dim or d.ndim
    # a site is active if ANY trailing-dim value is nonzero (sum would drop
    # sites whose values cancel, e.g. channels [1, -1])
    nz = np.nonzero((d.reshape(d.shape[:sd] + (-1,)) != 0).any(-1)
                    if sd < d.ndim else d)
    idx = jnp.asarray(np.stack(nz), jnp.int64)
    vals = apply("gather_nz", lambda a: a[nz], dense)
    return SparseCooTensor(idx, vals, d.shape)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


# ---------------------------------------------------------------------------
# binary / multiary (ref sparse/binary.py, multiary.py)
# ---------------------------------------------------------------------------

def _binary(name, jfn, same_pattern_only=False):
    def op(x, y, name=None):
        if _is_sparse(x) and _is_sparse(y):
            # same-structure fast path
            if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor) \
                    and x._indices.shape == y._indices.shape \
                    and bool(jnp.all(x._indices == y._indices)):
                vals = apply(name_, jfn, x._values, y._values)
                return SparseCooTensor(x._indices, vals, x._shape)
            if same_pattern_only:
                # densifying would evaluate x/0 and 0/0 over the union (NaNs)
                raise ValueError(
                    f"sparse {name_} requires identical sparsity patterns")
            dense = apply(name_, jfn, x.to_dense(), y.to_dense())
            return _dense_to_coo(dense)
        xd = x.to_dense() if _is_sparse(x) else x
        yd = y.to_dense() if _is_sparse(y) else y
        return apply(name_, jfn, xd, yd)
    name_ = name
    op.__name__ = name
    return op


add = _binary("sparse_add", jnp.add)
subtract = _binary("sparse_subtract", jnp.subtract)
multiply = _binary("sparse_multiply", jnp.multiply)
divide = _binary("sparse_divide", jnp.divide, same_pattern_only=True)


def matmul(x, y, name=None):
    """sparse @ dense -> dense (ref sparse matmul): gather rows by the sparse
    pattern and accumulate — one fused XLA scatter over an MXU matmul.
    Batched (>2-D) operands densify first (dense batched matmul IS the MXU
    path; the gather formulation only wins for the 2-D case)."""
    from ..ops.math import matmul as dmatmul
    if isinstance(x, SparseCsrTensor) and len(x._shape) == 2:
        x = x.to_sparse_coo()
    if isinstance(x, SparseCooTensor):
        yd = y.to_dense() if _is_sparse(y) else y
        ynd = yd._data.ndim if isinstance(yd, Tensor) else np.ndim(yd)
        if len(x._shape) > 2 or ynd != 2:
            return dmatmul(x.to_dense(), yd)
        rows, cols = x._indices[0], x._indices[1]
        M = x._shape[0]

        def f(v, b):
            contrib = v[:, None] * b[cols]           # [nnz, N]
            return jax.ops.segment_sum(contrib, rows.astype(jnp.int32),
                                       num_segments=M)
        return apply("sparse_matmul", f, x._values, yd)
    if _is_sparse(x):
        return dmatmul(x.to_dense(), y.to_dense() if _is_sparse(y) else y)
    # dense @ sparse: transpose trick (2-D); batched densifies
    if _is_sparse(y):
        xnd = x._data.ndim if isinstance(x, Tensor) else np.ndim(x)
        if xnd != 2 or len(y.shape) != 2:
            return dmatmul(x, y.to_dense())
        from ..ops.manipulation import transpose as dtr
        xt = dtr(x, [1, 0])
        yt = transpose(y, [1, 0])
        out = matmul(yt, xt)
        return dtr(out, [1, 0])
    return dmatmul(x, y)


def mv(x, vec, name=None):
    from ..ops.manipulation import unsqueeze, squeeze
    return squeeze(matmul(x, unsqueeze(vec, -1)), -1)


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) evaluated only at mask's sparsity pattern (ref
    masked_matmul -> SDDMM).  Gather the needed row/col pairs and batch the
    dot products — no [M, N] product materializes."""
    if isinstance(mask, SparseCsrTensor):
        coo = mask.to_sparse_coo()
        rows, cols = coo._indices[0], coo._indices[1]

        def f(a, b):
            return jnp.einsum("nd,nd->n", a[rows], b[:, cols].T)
        vals = apply("masked_matmul", f, x, y)
        return SparseCsrTensor(mask._crows, mask._cols, vals, mask._shape)
    rows, cols = mask._indices[0], mask._indices[1]

    def f(a, b):
        return jnp.einsum("nd,nd->n", a[rows], b[:, cols].T)
    vals = apply("masked_matmul", f, x, y)
    return SparseCooTensor(mask._indices, vals, mask._shape)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """ref sparse addmm: beta * input + alpha * (x @ y)."""
    prod = matmul(x, y)
    ind = input.to_dense() if _is_sparse(input) else input
    pd = prod.to_dense() if _is_sparse(prod) else prod
    return apply("sparse_addmm", lambda i, p: beta * i + alpha * p, ind, pd)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    from ..ops.linalg import pca_lowrank as dense_pca
    return dense_pca(x.to_dense() if _is_sparse(x) else x, q=q, center=center,
                     niter=niter)


from . import nn  # noqa  (sparse.nn layers)
