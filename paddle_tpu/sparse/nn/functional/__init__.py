"""paddle.sparse.nn.functional (ref python/paddle/sparse/nn/functional/)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor, apply
from ... import SparseCooTensor, SparseCsrTensor, _is_sparse

__all__ = ["attention", "conv2d", "conv3d", "leaky_relu", "max_pool3d", "relu",
           "relu6", "softmax", "subm_conv2d", "subm_conv3d"]


def _value_op(name, fn, x):
    if _is_sparse(x):
        vals = apply(name, fn, x.values())
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)
        return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
    return apply(name, fn, x)


def relu(x, name=None):
    return _value_op("sparse_relu", jax.nn.relu, x)


def relu6(x, name=None):
    return _value_op("sparse_relu6", lambda v: jnp.clip(v, 0.0, 6.0), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _value_op("sparse_leaky_relu",
                     lambda v: jnp.where(v >= 0, v, negative_slope * v), x)


def softmax(x, axis=-1, name=None):
    from .. import Softmax
    return Softmax(axis)(x)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    from .. import _dense_conv_sparse
    return _dense_conv_sparse_w(x, weight, bias, stride, padding, 2, False)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    return _dense_conv_sparse_w(x, weight, bias, stride, padding, 3, False)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _dense_conv_sparse_w(x, weight, bias, stride, padding, 2, True)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    return _dense_conv_sparse_w(x, weight, bias, stride, padding, 3, True)


def _dense_conv_sparse_w(x, weight, bias, stride, padding, dims, subm):
    """Functional form takes REFERENCE layout weights [*ks, Cin, Cout]
    (sparse/nn/functional/conv.py) and transposes to the layer layout
    [Cout, Cin, *ks] — no shape heuristics."""
    from .. import _dense_conv_sparse
    from ....ops.manipulation import transpose as tr
    perm = [dims + 1, dims] + list(range(dims))
    w = tr(weight, perm) if isinstance(weight, Tensor) \
        else Tensor(jnp.transpose(jnp.asarray(weight), perm))
    return _dense_conv_sparse(x, w, bias, stride, padding, dims, subm)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    from .. import MaxPool3D
    return MaxPool3D(kernel_size, stride, padding)(x)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """ref sparse/nn/functional/transformer.py attention: softmax(QK^T/sqrt(d))
    restricted to sparse_mask's CSR pattern, times V."""
    from ....nn.functional.sparse_ops import sparse_attention
    return sparse_attention(query, key, value, sparse_mask.crows(),
                            sparse_mask.cols())
