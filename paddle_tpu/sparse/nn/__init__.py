"""paddle.sparse.nn — layers over sparse tensors.

Reference parity: `python/paddle/sparse/nn/` (layer/activation.py, conv.py,
norm.py, pooling.py; kernels `phi/kernels/sparse/`).

TPU-native stance: activations/norms act on the explicit values (structure
preserved).  Sparse/submanifold convolutions densify the voxel grid and run
XLA's dense conv on the MXU, then re-sparsify — at the occupancies this API is
used for on TPU, dense conv with masking beats gather/scatter conv; submanifold
semantics (output pattern == input pattern) are preserved exactly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ...nn.layer.layers import Layer
from .. import SparseCooTensor, SparseCsrTensor, _dense_to_coo, _is_sparse
from . import functional  # noqa

__all__ = ["BatchNorm", "Conv2D", "Conv3D", "LeakyReLU", "MaxPool3D", "ReLU",
           "ReLU6", "Softmax", "SubmConv2D", "SubmConv3D", "SyncBatchNorm"]


class _ValueActivation(Layer):
    _fn = None
    _name = "act"

    def forward(self, x):
        if _is_sparse(x):
            vals = apply(self._name, type(self)._fn, x.values())
            if isinstance(x, SparseCooTensor):
                return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)
            return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
        return apply(self._name, type(self)._fn, x)


class ReLU(_ValueActivation):
    _fn = staticmethod(jax.nn.relu)
    _name = "sparse_relu"


class ReLU6(_ValueActivation):
    _fn = staticmethod(lambda v: jnp.clip(v, 0.0, 6.0))
    _name = "sparse_relu6"


class LeakyReLU(_ValueActivation):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        slope = self.negative_slope
        fn = lambda v: jnp.where(v >= 0, v, slope * v)  # noqa: E731
        if _is_sparse(x):
            vals = apply("sparse_leaky_relu", fn, x.values())
            if isinstance(x, SparseCooTensor):
                return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)
            return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
        return apply("sparse_leaky_relu", fn, x)


class Softmax(Layer):
    """CSR row-wise softmax over explicit values (ref sparse softmax)."""

    def __init__(self, axis=-1, name=None):
        super().__init__()
        assert axis == -1, "sparse softmax supports the last axis"

    def forward(self, x):
        if isinstance(x, SparseCsrTensor):
            rows = x._row_ids().astype(jnp.int32)
            n = x._shape[0]

            def f(v):
                mx = jax.ops.segment_max(v, rows, num_segments=n)
                e = jnp.exp(v - mx[rows])
                s = jax.ops.segment_sum(e, rows, num_segments=n)
                return e / s[rows]
            vals = apply("sparse_softmax", f, x.values())
            return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
        if isinstance(x, SparseCooTensor):
            # return in the input format (ref: format-preserving)
            return Softmax()(x.to_sparse_csr()).to_sparse_coo()
        from ...nn.functional.activation import softmax as dsoftmax
        return dsoftmax(x, axis=-1)


class BatchNorm(Layer):
    """BatchNorm over the channel (last) dim of COO values (ref sparse norm)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn.layer.norm import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum, epsilon=epsilon)

    def forward(self, x):
        if _is_sparse(x):
            vals = self._bn(x.values())
            return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)
        return self._bn(x)


SyncBatchNorm = BatchNorm


def _dense_conv_sparse(x, weight, bias, stride, padding, dims, subm):
    """Densify -> XLA conv -> re-sparsify (see module docstring)."""
    from ...nn.functional.conv import conv2d, conv3d
    dense = x.to_dense()                     # [N, *spatial, C] (NDHWC/NHWC)
    perm_in = (0, dims + 1) + tuple(range(1, dims + 1))       # -> NC...
    from ...ops.manipulation import transpose as tr
    xc = tr(dense, list(perm_in))
    conv = conv2d if dims == 2 else conv3d
    out = conv(xc, weight, bias, stride=stride, padding=padding)
    back = (0,) + tuple(range(2, dims + 2)) + (1,)            # -> N...C
    out = tr(out, list(back))
    if subm:
        # submanifold: output pattern == input pattern
        idx = x._indices
        sd = idx.shape[0]
        vals = apply("subm_gather", lambda a: a[tuple(idx[i] for i in range(sd))],
                     out)
        out_shape = tuple(out.shape)
        return SparseCooTensor(idx, vals, out_shape, x._coalesced)
    return _dense_to_coo(out, sparse_dim=dims + 1)


class _SparseConv(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False, dims=3,
                 weight_attr=None, bias_attr=None, data_format=None, name=None):
        super().__init__()
        from ...core.tensor import Parameter
        from ...core import generator as _gen
        ks = (kernel_size,) * dims if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        fan_in = in_channels
        for k in ks:
            fan_in *= k
        bound = (6.0 / fan_in) ** 0.5
        self.weight = Parameter(jax.random.uniform(
            _gen.next_key(), (out_channels, in_channels) + ks, jnp.float32,
            -bound, bound))
        self.add_parameter("weight", self.weight)
        self.bias = None
        if bias_attr is not False:
            self.bias = Parameter(jnp.zeros((out_channels,), jnp.float32))
            self.add_parameter("bias", self.bias)
        self._stride, self._padding = stride, padding
        self._subm, self._dims = subm, dims

    def forward(self, x):
        return _dense_conv_sparse(x, self.weight, self.bias, self._stride,
                                  self._padding, self._dims, self._subm)


class Conv2D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        kw.setdefault("dims", 2)
        super().__init__(in_channels, out_channels, kernel_size, **kw)


class Conv3D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        kw.setdefault("dims", 3)
        super().__init__(in_channels, out_channels, kernel_size, **kw)


class SubmConv2D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        kw.setdefault("dims", 2)
        kw["subm"] = True
        super().__init__(in_channels, out_channels, kernel_size, **kw)


class SubmConv3D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        kw.setdefault("dims", 3)
        kw["subm"] = True
        super().__init__(in_channels, out_channels, kernel_size, **kw)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding)

    def forward(self, x):
        from ...nn.functional.pooling import max_pool3d
        from ...ops.manipulation import transpose as tr
        k, s, p = self._args
        dense = x.to_dense() if _is_sparse(x) else x      # NDHWC
        xc = tr(dense, [0, 4, 1, 2, 3])
        out = max_pool3d(xc, k, s if s is not None else k, p)
        out = tr(out, [0, 2, 3, 4, 1])
        return _dense_to_coo(out, sparse_dim=4) if _is_sparse(x) else out
