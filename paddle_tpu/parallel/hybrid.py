"""Compiled hybrid-parallel trainer: dp × pp × mp (+ZeRO, +remat) in ONE jitted step.

This is the TPU-native answer to the reference's hybrid stack
(`fleet/meta_parallel/` DP reducer + mpu TP layers + `pipeline_parallel.py` 1F1B +
sharding optimizer):

- **dp / mp**: GSPMD.  Parameters carry NamedShardings (mp = Megatron layout: qkv/fc1
  column-split, proj/fc2 row-split, vocab-split embedding); the batch is sharded over
  dp; XLA inserts the exact allreduce/allgather/reduce-scatter set the reference codes
  by hand in mp_ops.py and the DP reducer — fused into the backward schedule.
- **pp**: a GPipe microbatch loop written with `shard_map_compat(axis_names={'pp'})` +
  `ppermute` inside the SAME jitted program — stages exchange activations over ICI
  each tick; `jax.grad` differentiates through the scan, producing the reverse
  pipeline automatically (the reference's hand-written 1F1B send/recv schedule,
  `pp_utils/p2p_communication.py`, becomes ~30 lines).
- **ZeRO stage-1**: optimizer moments get NamedShardings split over dp
  (`DygraphShardingOptimizer` parity, but it's just a sharding annotation here).
- **sp (sequence parallel)**: activations outside attention are sharded over mp on
  the sequence axis via sharding constraints when `sequence_parallel=True`.
- **remat**: `jax.checkpoint` around each block (`recompute` parity).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import gpt as gpt_mod
from .ring_attention import shard_map_compat


@dataclasses.dataclass
class MeshConfig:
    dp: int = 1
    pp: int = 1
    sharding: int = 1            # ZeRO axis degree (ref topology.py:61 axis order)
    mp: int = 1
    ep: int = 1                  # expert-parallel degree (MoE all-to-all group)
    cp: int = 1                  # context-parallel degree (ring attention)
    vpp: int = 1                 # virtual pipeline chunks per stage (interleave)
    sharding_stage: int = 1      # ZeRO stage: 1=opt state, 2=+grads, 3=+params
    micro_batches: int = 1       # pipeline microbatches (per global step)
    sequence_parallel: bool = False
    remat: bool = False

    @property
    def size(self):
        return self.dp * self.pp * self.sharding * self.mp * self.ep * self.cp

    @property
    def zero_axis(self):
        """Axis the optimizer state shards over: the dedicated 'sharding' axis
        when present, else dp (pure-dp ZeRO-1, the round-1 behavior)."""
        if self.sharding > 1:
            return "sharding"
        return "dp" if self.dp > 1 else None


def build_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    devs = np.array(devices if devices is not None else jax.devices()[:cfg.size])
    assert devs.size >= cfg.size, f"need {cfg.size} devices, have {devs.size}"
    # axis order mirrors the reference hybrid topology ["data","pipe","sharding",
    # "model"] (fleet/base/topology.py:61) with the MoE 'ep' and ring 'cp' axes
    # innermost so their all-to-all/ppermute ride adjacent ICI links
    return Mesh(devs[:cfg.size].reshape(cfg.dp, cfg.pp, cfg.sharding, cfg.mp,
                                        cfg.ep, cfg.cp),
                ("dp", "pp", "sharding", "mp", "ep", "cp"))


# ---------------------------------------------------------------------------
# sharding rules for the GPT params pytree (Megatron layout)
# ---------------------------------------------------------------------------

def gpt_param_specs(cfg: MeshConfig, model_config=None):
    pp = "pp" if cfg.pp > 1 else None
    mp = "mp" if cfg.mp > 1 else None
    ep = "ep" if cfg.ep > 1 else None
    use_bias = model_config is None or model_config.use_bias
    blocks = {
        "ln1_w": P(pp, None), "ln1_b": P(pp, None),
        "qkv_w": P(pp, None, mp),
        "proj_w": P(pp, mp, None),
        "ln2_w": P(pp, None), "ln2_b": P(pp, None),
    }
    if use_bias:
        blocks.update({"qkv_b": P(pp, mp), "proj_b": P(pp, None)})
    if model_config is not None and model_config.moe_num_experts > 0:
        # experts shard over 'ep' on the E dim (ref: experts distributed across
        # the moe_group ranks, dispatched via global_scatter) — router replicated
        blocks.update({
            "gate_w": P(pp, None, None),
            "exp_fc1_w": P(pp, ep, None, None), "exp_fc1_b": P(pp, ep, None),
            "exp_fc2_w": P(pp, ep, None, None), "exp_fc2_b": P(pp, ep, None),
        })
    else:
        blocks.update({
            "fc1_w": P(pp, None, mp),
            "fc2_w": P(pp, mp, None),
        })
        if use_bias:
            blocks.update({"fc1_b": P(pp, mp), "fc2_b": P(pp, None)})
        if model_config is not None and model_config.gated_ffn:
            # gate projection is column-split like fc1 (Megatron SwiGLU layout)
            blocks["fcg_w"] = P(pp, None, mp)
            if use_bias:
                blocks["fcg_b"] = P(pp, mp)
    specs = {
        "wte": P(mp, None),
        "blocks": blocks,
        "lnf_w": P(None), "lnf_b": P(None),
    }
    if cfg.sharding_stage >= 3 and cfg.sharding > 1:
        # ZeRO-3 / FSDP: params shard over the 'sharding' axis at rest; XLA
        # inserts the gather at each use site and the reduce-scatter on grads
        # (ref GroupShardedStage3 gather-on-demand, group_sharded_stage3.py).
        # Only the transformer blocks (the bulk of the params): fsdp-sharding the
        # vocab-sharded embedding turns the token lookup into a gather XLA's SPMD
        # partitioner can't device-group (CHECK crash at dp>1), the standard
        # exclude-embeddings-from-FSDP caveat.
        specs["blocks"] = _add_axis_everywhere(blocks, "sharding")
    return specs


def serving_mesh(mp: int, devices=None) -> Mesh:
    """1-D tensor-parallel mesh for the serving engine: the first `mp` devices
    on an ("mp",) axis — the decode path has no batch/pipeline dimension worth
    sharding (num_slots is small and latency-critical), so serving uses a pure
    Megatron mp slice of the machine."""
    devs = np.array(devices if devices is not None else jax.devices()[:mp])
    assert devs.size >= mp, f"need {mp} devices for mp serving, have {devs.size}"
    return Mesh(devs[:mp], ("mp",))


def serving_param_specs(model_config, params):
    """PartitionSpec tree (congruent with `params`) for tensor-parallel
    serving: the trainer's Megatron block layout (`gpt_param_specs` with the
    pp/ep axes off — qkv/fc1/fcg column-split, proj/fc2 row-split) over an
    ("mp",) serving mesh, with the embedding table and LM head VOCAB-SHARDED
    (`wte` rows / `lm_head` columns split over "mp", the Megatron
    vocab-parallel layout — ref fleet/layers/mpu.py).

    The vocab shard is what retires the repo's last replicated-memory
    ceiling: since the fused step samples ON DEVICE, the head never needs
    replicated [B, V] logits — the embed runs as a masked local take + psum
    (`models.gpt._embed`, mirroring the trainer's `_vp_embed`), the head
    matmul consumes the local shard producing [.., V/mp] logits, and the
    argmax/top-k/sample pick merges per-shard (value, global index) pairs
    (`models.gpt.sharded_argmax` / `sample_token`).  Only the tiny
    position/norm vectors (wpe, lnf) remain replicated.

    Weight-quantized params (`quantization.serving.quantize_serving_params`)
    replace a weight with the `name_q` (int8) + `name_scale` (f32) pair: the
    int8 leaf keeps the fp weight's spec, and the scale shards WITH the
    weight's quantization channel dim — block scales are [L, 1, out] and
    split with column-parallel outputs (qkv/fc1/fcg), replicated for
    row-parallel proj/fc2; the head pairs shard with their vocab dim
    (`wte_scale` [V, 1] rows, `lm_head_scale` [1, V] columns), so dequant
    stays a shard-local elementwise multiply."""
    base = gpt_param_specs(MeshConfig(mp=2), model_config)["blocks"]

    def block_spec(k):
        if k.endswith("_q"):
            return base.get(k[:-2], P())
        if k.endswith("_scale"):
            wspec = base.get(k[:-len("_scale")], P())
            last = wspec[2] if len(wspec) > 2 else None
            return P(None, None, "mp") if last is not None else P()
        return base.get(k, P())

    vocab = {
        # wte is [V, D] row-sharded; its int8 twin and [V, 1] scale follow.
        "wte": P("mp", None), "wte_q": P("mp", None),
        "wte_scale": P("mp", None),
        # untied lm_head is [D, V] column-sharded; scale is [1, V].
        "lm_head": P(None, "mp"), "lm_head_q": P(None, "mp"),
        "lm_head_scale": P(None, "mp"),
    }
    blocks = {k: block_spec(k) for k in params["blocks"]}
    specs = {k: vocab.get(k, P()) for k in params if k != "blocks"}
    specs["blocks"] = blocks
    return specs


def qkv_partition_perm(model_config, parts: int) -> np.ndarray:
    """Column permutation taking the packed `[q | k | v]` qkv layout to the
    per-partition `[q_0 k_0 v_0 | q_1 k_1 v_1 | ...]` layout whose `parts`
    contiguous column groups are exactly each mp shard's head slices.

    The trainer packs qkv as one [D, (H + 2*KVH) * hd] matmul with q, k, v
    column groups laid out globally — under the serving spec
    P(None, None, "mp") a contiguous split then lands q/k/v FRAGMENTS on
    each chip and GSPMD must stage a replicate→reslice to reassemble the
    per-head layout at the split points (ROADMAP item-3c's named blocker).
    Permuting columns once at placement time makes the contiguous shard r
    hold precisely [q_r | k_r | v_r]; the model-side unpack
    (`models.gpt._unpack_qkv`) is partition-aware and restores GLOBAL head
    order bit-exactly, so the permutation is invisible to outputs."""
    H = model_config.num_heads
    KVH = model_config.kv_heads
    hd = model_config.head_dim
    assert H % parts == 0 and KVH % parts == 0, (H, KVH, parts)
    q = np.arange(H * hd).reshape(parts, -1)
    k = H * hd + np.arange(KVH * hd).reshape(parts, -1)
    v = (H + KVH) * hd + np.arange(KVH * hd).reshape(parts, -1)
    return np.concatenate([q, k, v], axis=1).reshape(-1)


def pack_qkv_partitions(params, model_config, parts: int):
    """Permute every packed-qkv leaf (fp weight, bias, int8 twin + channel
    scale) into the per-partition column layout (`qkv_partition_perm`), so
    `device_put` under `serving_param_specs` lands each chip's qkv shard
    without replicate→reslice staging.  `parts <= 1` is the identity."""
    if parts <= 1:
        return params
    perm = qkv_partition_perm(model_config, parts)
    blocks = dict(params["blocks"])
    for k in ("qkv_w", "qkv_b", "qkv_w_q", "qkv_w_scale"):
        if k in blocks:
            blocks[k] = blocks[k][..., perm]
    out = dict(params)
    out["blocks"] = blocks
    return out


def _add_axis(spec: P, shape, axis_name: str, degree: int) -> P:
    """Shard `axis_name` onto the first unsharded, divisible dim of `shape`."""
    flat = [a for e in spec if e is not None
            for a in (e if isinstance(e, tuple) else (e,))]
    if axis_name in flat:
        return spec  # already sharded over this axis (e.g. ZeRO-3 params)
    spec_l = list(spec) + [None] * (len(shape) - len(spec))
    for i, (s, cur) in enumerate(zip(shape, spec_l)):
        if cur is None and s % degree == 0 and s >= degree:
            spec_l[i] = axis_name
            break
    return P(*spec_l)


def _add_axis_everywhere(specs, axis_name):
    """Mark specs for late binding: actual dim choice needs shapes, resolved in
    the trainer where param shapes are known."""
    return jax.tree_util.tree_map(lambda sp: ("__add__", axis_name, sp), specs,
                                  is_leaf=lambda x: isinstance(x, P))


def _resolve_spec(marked, shape, cfg: MeshConfig):
    if isinstance(marked, tuple) and len(marked) == 3 and marked[0] == "__add__":
        _, axis_name, sp = marked
        return _add_axis(sp, shape, axis_name, cfg.sharding)
    return marked


def _opt_state_spec(param_spec: P, shape, cfg: MeshConfig):
    """ZeRO-1: shard optimizer moments over the zero axis on the first dim that is
    unsharded and divisible (ref DygraphShardingOptimizer owner assignment)."""
    axis = cfg.zero_axis
    if cfg.sharding_stage < 1 or axis is None:
        return param_spec
    degree = cfg.sharding if axis == "sharding" else cfg.dp
    return _add_axis(param_spec, shape, axis, degree)


# ---------------------------------------------------------------------------
# expert parallelism: global_scatter/global_gather over the 'ep' axis
# ---------------------------------------------------------------------------

def _moe_local(bp_local, x_l, config, ep: int):
    """Per-ep-rank MoE FFN body: the TPU-native global_scatter/global_gather
    (ref fluid/operators/collective/global_scatter_op.cc).

    Runs INSIDE a manual 'ep' region: x_l [T_l, D] is this rank's token shard and
    bp_local holds this rank's E/ep experts (gate replicated).  Each rank routes
    its tokens into per-expert capacity buffers, a tiled `all_to_all` hands every
    expert its queue slices from all ranks, the batched expert MLP runs on the
    owner, and the reverse all-to-all returns outputs for the local combine.
    Returns (y_l, aux_local) — caller aggregates aux over ep.
    """
    from ..incubate.distributed.models.moe.dispatch import (
        capacity_slots, combine, dispatch, expert_ffn, moe_capacity, topk_gating)

    E, k = config.moe_num_experts, config.moe_topk
    assert E % ep == 0, f"experts {E} must divide over ep={ep}"
    Tl, D = x_l.shape
    C = moe_capacity(Tl, k, E, config.moe_capacity_factor)
    gate_idx, gate_val, aux = topk_gating(jnp.matmul(x_l, bp_local["gate_w"]), k)
    slot, keep = capacity_slots(gate_idx, E, C)
    buf = dispatch(x_l, slot, E, C)                       # [E, C, D]
    if ep > 1:
        # global_scatter: chunk j (experts j*El..) -> rank j; received chunks
        # stack along capacity, source-rank-major -> [E/ep, ep*C, D]
        buf = jax.lax.all_to_all(buf, "ep", split_axis=0, concat_axis=1,
                                 tiled=True)
    out = expert_ffn(buf, bp_local["exp_fc1_w"], bp_local["exp_fc1_b"],
                     bp_local["exp_fc2_w"], bp_local["exp_fc2_b"],
                     config.activation)
    if ep > 1:
        # global_gather: return each rank its C-slice of every expert queue
        out = jax.lax.all_to_all(out, "ep", split_axis=1, concat_axis=0,
                                 tiled=True)              # [E, C, D]
    y = combine(out, slot, keep, gate_val)
    return y, aux


_MOE_EXPERT_KEYS = ("exp_fc1_w", "exp_fc1_b", "exp_fc2_w", "exp_fc2_b")


def _moe_ffn_ep(bp, x, config, cfg: MeshConfig, mesh):
    """GSPMD-path wrapper: shard_map the manual 'ep' MoE body over x [T, D]."""

    def local(gate_w, f1w, f1b, f2w, f2b, x_l):
        bp_local = {"gate_w": gate_w, "exp_fc1_w": f1w, "exp_fc1_b": f1b,
                    "exp_fc2_w": f2w, "exp_fc2_b": f2b}
        y, aux = _moe_local(bp_local, x_l, config, cfg.ep)
        return y, jax.lax.psum(aux, "ep") / cfg.ep

    return shard_map_compat(
        local, mesh=mesh, axis_names={"ep"},
        in_specs=(P(), P("ep"), P("ep"), P("ep"), P("ep"), P("ep")),
        out_specs=(P("ep"), P()))(
            bp["gate_w"], bp["exp_fc1_w"], bp["exp_fc1_b"],
            bp["exp_fc2_w"], bp["exp_fc2_b"], x)


# ---------------------------------------------------------------------------
# context-parallel loss: sequence sharded over 'cp', ring attention inside
# ---------------------------------------------------------------------------

def _cp_loss(params, tokens, labels, config, cfg: MeshConfig, mesh):
    """Long-context training: tokens/labels [B, S] with S sharded over 'cp';
    every block's attention runs the ring (SURVEY §7.10 — beyond-reference)."""
    import functools

    from .ring_attention import ring_attention_local

    cp = cfg.cp
    B, S = tokens.shape
    Sl = S // cp
    assert S % cp == 0, f"seq len {S} must divide over cp={cp}"
    attn = functools.partial(ring_attention_local, axis_name="cp", cp=cp,
                             causal=True)

    # embedding + LM head run OUTSIDE the manual cp region so the existing
    # vocab-parallel shard_maps handle the mp-sharded table (a vocab-sharded
    # gather under auto axes CHECK-crashes XLA's partitioner)
    x = _vp_embed(params["wte"], tokens, mesh, cfg)
    if not config.use_rope:
        x = x + params["wpe"][:S]

    def local(blocks, lnf_w, lnf_b, x_l):
        r = jax.lax.axis_index("cp")
        offset = r * Sl
        x_l, aux = gpt_mod.run_blocks(blocks, x_l, config, remat=cfg.remat,
                                      attn_impl=attn, pos_offset=offset)
        h = gpt_mod._norm(x_l, lnf_w, lnf_b, config)
        return h, jax.lax.psum(aux, "cp")

    blk_specs = jax.tree_util.tree_map(lambda _: P(), params["blocks"])
    h, aux = shard_map_compat(
        local, mesh=mesh, axis_names={"cp"},
        in_specs=(blk_specs, P(), P(), P(None, "cp", None)),
        out_specs=(P(None, "cp", None), P()))(
            params["blocks"], params["lnf_w"], params["lnf_b"], x)
    head = params["wte"].T if config.tie_word_embeddings else params["lm_head"]
    loss = _vp_ce(h, head, labels, mesh, cfg)
    if config.moe_num_experts > 0:
        # psum summed cp per-shard aux values; mean matches the dense scale
        loss = loss + config.moe_aux_weight * aux / cp
    return loss


# ---------------------------------------------------------------------------
# pipeline loop (manual over 'pp', GSPMD over dp/mp)
# ---------------------------------------------------------------------------

def _vp_embed(wte, tokens, mesh, cfg: MeshConfig):
    """Vocab-parallel embedding (ref VocabParallelEmbedding, mp_layers.py:35):
    masked local lookup on the mp-sharded table + psum.  Keeps the gather fully
    local so XLA's SPMD partitioner never sees a vocab-sharded gather (which it
    CHECK-crashes on at 4 live mesh axes)."""
    if cfg.mp <= 1:
        return jnp.take(wte, tokens, axis=0)

    def local(wte_l, tok):
        r = jax.lax.axis_index("mp")
        Vl = wte_l.shape[0]
        ids = tok - r * Vl
        ok = (ids >= 0) & (ids < Vl)
        safe = jnp.clip(ids, 0, Vl - 1)
        e = jnp.take(wte_l, safe, axis=0)
        e = jnp.where(ok[..., None], e, jnp.zeros((), e.dtype))
        return jax.lax.psum(e, "mp")

    return shard_map_compat(local, mesh=mesh, axis_names={"mp"},
                         in_specs=(P("mp", None), P()), out_specs=P())(wte, tokens)


def _vp_ce(h, head, labels, mesh, cfg: MeshConfig):
    """Cross entropy with the vocab dim mp-sharded and (when divisible) the batch
    dim pp-sharded — every device computes head flops exactly once per token (ref
    ParallelCrossEntropy, mp_layers.py:524)."""
    manual = set()
    batch_axes = ()
    if cfg.pp > 1 and h.shape[0] % cfg.pp == 0:
        manual.add("pp")
        batch_axes = ("pp",)
        # with an ep axis live, leaving it auto makes XLA's gather partitioner
        # CHECK-crash on the label pick; fold it into the manual batch split,
        # or fall back to the dense CE when the batch doesn't divide
        if cfg.ep > 1:
            if h.shape[0] % (cfg.pp * cfg.ep) == 0:
                manual.add("ep")
                batch_axes = ("pp", "ep")
            else:
                manual.discard("pp")
                batch_axes = ()
    # cp shards the SEQUENCE dim; like ep, leaving it auto crashes the gather
    # partitioner when another manual axis is live
    seq_axes = ()
    if cfg.cp > 1 and "pp" in manual and h.shape[1] % cfg.cp == 0:
        manual.add("cp")
        seq_axes = ("cp",)
    if cfg.mp > 1:
        manual.add("mp")
    if not manual:
        loss_sum, n = gpt_mod._ce_sums(jnp.matmul(h, head), labels)
        return loss_sum / jnp.maximum(n, 1.0)

    have_mp = "mp" in manual

    def local(h_l, head_l, lab_l):
        logits = jnp.matmul(h_l, head_l).astype(jnp.float32)  # [b_l, S, V_l]
        # max shift is stability-only and cancels out of lse - pick; stop_gradient
        # also sidesteps pmax's missing differentiation rule
        mx = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
        if have_mp:
            mx = jax.lax.pmax(mx, "mp")
        se = jnp.sum(jnp.exp(logits - mx[..., None]), axis=-1)
        if have_mp:
            se = jax.lax.psum(se, "mp")
        lse = mx + jnp.log(se)
        if have_mp:
            r = jax.lax.axis_index("mp")
            Vl = head_l.shape[-1]
            ids = lab_l - r * Vl
            ok = (ids >= 0) & (ids < Vl)
            safe = jnp.clip(ids, 0, Vl - 1)
            pick = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
            pick = jax.lax.psum(jnp.where(ok, pick, 0.0), "mp")
        else:
            safe = jnp.where(lab_l < 0, 0, lab_l)
            pick = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        mask = (lab_l >= 0).astype(jnp.float32)
        ls = jnp.sum((lse - pick) * mask)
        n = jnp.sum(mask)
        if batch_axes or seq_axes:
            ls = jax.lax.psum(ls, batch_axes + seq_axes)
            n = jax.lax.psum(n, batch_axes + seq_axes)
        return ls, n

    spec_b = P(batch_axes if batch_axes else None,
               seq_axes if seq_axes else None)
    spec_head = P(None, "mp") if have_mp else P()
    ls, n = shard_map_compat(local, mesh=mesh, axis_names=manual,
                          in_specs=(spec_b, spec_head, spec_b),
                          out_specs=(P(), P()))(h, head, labels)
    return ls / jnp.maximum(n, 1.0)


def _pp_loss(params, tokens, labels, config, cfg: MeshConfig, mesh):
    """Pipeline-parallel loss: vocab-parallel embed -> microbatch loop over 'pp'
    via shard_map+ppermute -> last-stage outputs -> vocab/batch-parallel CE.

    Schedule note (ref 1F1B, pipeline_parallel.py:387): the forward is a GPipe
    sweep, but under jax.grad XLA reverses the tick scan, so backward ticks run
    newest-microbatch-first exactly like 1F1B cooldown, and per-tick residency is
    only the boundary activation stack (per-block internals rematerialize via
    run_blocks' checkpoint policy) — the 1F1B memory profile without the
    hand-written send/recv schedule.  The LM head runs once per token, sharded
    over pp (microbatches) and mp (vocab) — no per-tick head waste.

    Interleaving (cfg.vpp > 1, ref PipelineParallelWithInterleave :822): each
    stage holds vpp NON-CONTIGUOUS layer chunks (chunk c covers layers
    [c*P*Lc + p*Lc, ...]); every tick runs ONE chunk, 1/vpp of a GPipe tick, and
    the Megatron closed-form schedule (device p delayed p ticks, work order
    g-major then chunk then slot) makes every ring hand-off arrive exactly one
    tick ahead of use.  Warmup/cooldown ticks shrink from (P-1) full-stage
    ticks to (P-1) chunk ticks — the pipeline bubble drops by vpp."""
    M = cfg.micro_batches
    Ppp = cfg.pp
    B, S = tokens.shape
    assert B % M == 0, \
        f"batch {B} must divide into micro_batches={M} (pad the batch; " \
        "uneven microbatches are not supported)"
    mb = B // M
    D = config.hidden_size
    # MoE with ep runs in the SAME manual region as pp (shardy requires manual
    # axes to be declared together rather than nested), so each (pp, ep) rank
    # routes its microbatch shard and all_to_all's over 'ep' inside the tick
    moe_manual = config.moe_num_experts > 0 and cfg.ep > 1
    cp_manual = cfg.cp > 1
    manual = ("pp",) + (("ep",) if moe_manual else ()) + \
        (("cp",) if cp_manual else ())
    if moe_manual:
        assert mb % cfg.ep == 0, f"microbatch {mb} must divide over ep={cfg.ep}"
    if cp_manual:
        assert not moe_manual, "cp x ep is not supported yet"
        assert S % cfg.cp == 0, f"seq len {S} must divide over cp={cfg.cp}"
    mb_l = mb // cfg.ep if moe_manual else mb
    S_l = S // cfg.cp if cp_manual else S
    moe_impl = (lambda bpl, xl, c: _moe_local(bpl, xl, c, cfg.ep)) \
        if moe_manual else None

    x = _vp_embed(params["wte"], tokens, mesh, cfg)
    if not config.use_rope:
        x = x + params["wpe"][:S]
    xs = x.reshape(M, mb, S, D)

    vpp = cfg.vpp
    if vpp > 1:
        assert M % Ppp == 0, \
            f"interleaved schedule needs micro_batches {M} % pp {Ppp} == 0"
        assert config.num_layers % (Ppp * vpp) == 0, \
            f"layers {config.num_layers} must divide over pp*vpp"
        # chunk c of stage p = layers [(c*Ppp + p) * Lc, ...): reshape the
        # stacked layer axis to [vpp, Ppp, Lc] and shard the Ppp axis.  The
        # reshape INTERLEAVES layers across the new dims, so the params' at-rest
        # (pp, ..., mp) sharding cannot be pushed through it — the partitioner
        # used to fall back to involuntary full rematerialization (the [SPMD]
        # warnings in MULTICHIP_r03.json).  Stage it explicitly instead:
        # allgather to replicated, reshape, reslice onto pp — each transition
        # is one the partitioner lowers efficiently.  The mp allgather is not
        # extra work: the shard_map below consumes P(None, "pp") inputs, so
        # axes outside pp were ALWAYS replicated at this boundary (the PR-1
        # full-manual fallback computes redundantly per mp rank by design).
        def _vpp_reshape(a):
            a = jax.lax.with_sharding_constraint(a, NamedSharding(mesh, P()))
            a = a.reshape((vpp, Ppp, a.shape[0] // (vpp * Ppp)) + a.shape[1:])
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, P(None, "pp")))

        blocks_arg = jax.tree_util.tree_map(_vpp_reshape, params["blocks"])
        T = vpp * M + Ppp - 1
    else:
        blocks_arg = params["blocks"]
        T = M + Ppp - 1

    attn_impl = None
    if cp_manual:
        from .ring_attention import ring_attention_local
        attn_impl = functools.partial(ring_attention_local, axis_name="cp",
                                      cp=cfg.cp, causal=True)

    def local_fn(blocks_local, xs_rep):
        p = jax.lax.axis_index("pp")
        pos_offset = jax.lax.axis_index("cp") * S_l if cp_manual else None

        def tick(carry, t):
            buf, aux_acc = carry
            if vpp > 1:
                u = t - p                  # this device's schedule position
                uc = jnp.clip(u, 0, vpp * M - 1)
                g = uc // (vpp * Ppp)      # microbatch group
                r = uc % (vpp * Ppp)
                c = r // Ppp               # virtual chunk
                m = g * Ppp + (r % Ppp)    # microbatch index
                chunk = jax.tree_util.tree_map(lambda a: a[c][0], blocks_local)
                inject = (p == 0) & (c == 0)
                valid = ((u >= 0) & (u < vpp * M))
            else:
                chunk = blocks_local
                m = jnp.clip(t, 0, M - 1)
                inject = p == 0
                valid = (t >= p) & (t < p + M)
            inp = jnp.where(inject, xs_rep[m], buf)
            out, aux = gpt_mod.run_blocks(chunk, inp, config,
                                          remat=cfg.remat, moe_impl=moe_impl,
                                          attn_impl=attn_impl,
                                          pos_offset=pos_offset)
            nxt = jax.lax.ppermute(out, "pp",
                                   [(i, (i + 1) % Ppp) for i in range(Ppp)])
            # invalid (warmup/cooldown) ticks run on garbage; mask their aux
            return (nxt, aux_acc + (aux * valid.astype(aux.dtype))[None]), out

        buf0 = gpt_mod.pvary_compat(jnp.zeros((mb_l, S_l, D), xs_rep.dtype),
                                    manual)
        # aux rides the boundary rank-1: old-JAX shard_map autodiff fails
        # to promote scalar residuals (_SpecError), and a (1,) lane is free
        aux0 = gpt_mod.pvary_compat(jnp.zeros((1,), jnp.float32), manual)
        (_, aux_sum), outs = jax.lax.scan(tick, (buf0, aux0), jnp.arange(T))
        # drop warmup/cooldown garbage IN-shard: only M ticks (and their grad
        # cotangents) cross the shard_map boundary.  The finish ticks are
        # static; only the LAST stage's slice is consumed downstream, but every
        # stage must slice identically for a uniform out_spec.
        if vpp == 1:
            outs = outs[Ppp - 1:]
        else:
            finish = [(m // Ppp) * vpp * Ppp + (vpp - 1) * Ppp + (m % Ppp)
                      + Ppp - 1 for m in range(M)]
            outs = outs[np.asarray(finish)]
        return outs, jax.lax.psum(aux_sum, manual)

    if vpp > 1:
        # vpp reshape puts experts' E on dim 3: [vpp, Ppp, Lc, E, ...]
        blk_in = {k: (P(None, "pp", None, "ep") if (moe_manual and
                                                    k in _MOE_EXPERT_KEYS)
                      else P(None, "pp"))
                  for k in params["blocks"]}
    else:
        blk_in = {k: (P("pp", "ep") if (moe_manual and k in _MOE_EXPERT_KEYS)
                      else P("pp"))
                  for k in params["blocks"]}
    xs_spec = P(None, "ep" if moe_manual else None,
                "cp" if cp_manual else None)
    out_spec = P("pp", "ep" if moe_manual else None,
                 "cp" if cp_manual else None)
    f = shard_map_compat(
        local_fn, mesh=mesh, axis_names=set(manual),
        in_specs=(blk_in, xs_spec),
        out_specs=(out_spec, P()))
    stacked_all, aux_sum = f(blocks_arg, xs)   # [Ppp*M, mb, S, D]
    aux_sum = aux_sum[0]
    if moe_manual:
        aux_sum = aux_sum / cfg.ep
    # each stage contributed M sliced ticks; the last stage's hold finished
    # microbatches 0..M-1 in order
    hs = stacked_all[(Ppp - 1) * M:]           # [M, mb, S, D]
    h = gpt_mod._norm(hs.reshape(B, S, D), params["lnf_w"], params["lnf_b"],
                      config)
    head = params["wte"].T if config.tie_word_embeddings else params["lm_head"]
    loss = _vp_ce(h, head, labels, mesh, cfg)
    if config.moe_num_experts > 0:
        # aux_sum covers all M microbatches (and, with cp, all cp seq shards);
        # average to match the dense scale
        loss = loss + config.moe_aux_weight * aux_sum / (M * cfg.cp)
    return loss


# ---------------------------------------------------------------------------
# trainer
# ---------------------------------------------------------------------------

class HybridParallelTrainer:
    """Owns mesh + sharded params/opt-state + the ONE jitted train step."""

    def __init__(self, config: gpt_mod.GPTConfig, mesh_cfg: MeshConfig,
                 learning_rate=1e-4, weight_decay=0.01, beta1=0.9, beta2=0.95,
                 grad_clip_norm: Optional[float] = 1.0, seed=0, devices=None,
                 moment_dtype=jnp.float32):
        self.config = config
        self.cfg = mesh_cfg
        self.mesh = build_mesh(mesh_cfg, devices)
        self.lr = learning_rate
        self.wd = weight_decay
        self.betas = (beta1, beta2)
        self.clip_norm = grad_clip_norm
        self.moment_dtype = moment_dtype

        specs = gpt_param_specs(mesh_cfg, config)
        if not config.use_rope:
            specs["wpe"] = P(None, None)
        if not config.tie_word_embeddings:
            specs["lm_head"] = P(None, "mp" if mesh_cfg.mp > 1 else None)
        # late-bind ZeRO-3 param sharding (needs the shapes)
        shapes = jax.eval_shape(functools.partial(gpt_mod.init_params, config),
                                jax.random.key(0))
        is_marked = lambda x: isinstance(x, P) or (
            isinstance(x, tuple) and len(x) == 3 and x[0] == "__add__")
        specs = jax.tree_util.tree_map(
            lambda sp, sh: _resolve_spec(sp, sh.shape, mesh_cfg), specs, shapes,
            is_leaf=is_marked)
        self.param_specs = specs
        self.param_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))

        key = jax.random.key(seed)
        init = jax.jit(functools.partial(gpt_mod.init_params, config),
                       out_shardings=self.param_shardings)
        self.params = init(key)

        m_shardings = jax.tree_util.tree_map(
            lambda l, s: NamedSharding(self.mesh, _opt_state_spec(s, l.shape, mesh_cfg)),
            self.params, specs)
        self._m_shardings = m_shardings
        mdt = moment_dtype
        init_opt = jax.jit(
            lambda p: {"m": jax.tree_util.tree_map(lambda x: jnp.zeros_like(x, mdt), p),
                       "v": jax.tree_util.tree_map(lambda x: jnp.zeros_like(x, mdt), p),
                       "step": jnp.zeros((), jnp.int32)},
            out_shardings={"m": m_shardings, "v": m_shardings, "step": None})
        self.opt_state = init_opt(self.params)
        self._step_fn = self._build_step()
        self._eval_fn = None    # built lazily on first eval_loss

    # ---- sharding constraint hook handed to the model ----
    def _mp_constraint(self, x, kind):
        cfg = self.cfg
        if cfg.mp <= 1:
            return x
        if kind in ("hidden_mp", "ffn_mp"):
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(self.mesh, P(("dp", "sharding", "ep"), None, "mp")))
        if kind == "act" and cfg.sequence_parallel:
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(self.mesh, P(("dp", "sharding", "ep"), "mp", None)))
        return x

    def _build_step(self):
        config = self.config
        cfg = self.cfg
        mesh = self.mesh
        lr, wd = self.lr, self.wd
        b1, b2 = self.betas
        clip = self.clip_norm

        moe_impl = None
        if config.moe_num_experts > 0 and cfg.ep > 1:
            moe_impl = functools.partial(_moe_ffn_ep, cfg=cfg, mesh=mesh)

        if cfg.cp > 1:
            assert cfg.ep == 1, "cp x ep is not supported yet"
        if cfg.vpp > 1:
            assert cfg.pp > 1, \
                "vpp (interleaved virtual stages) requires pp > 1 (ref: " \
                "virtual_pp_degree needs pipeline parallelism)"

        def loss_of(params, tokens, labels):
            if cfg.pp > 1:
                return _pp_loss(params, tokens, labels, config, cfg, mesh)
            if cfg.cp > 1:
                return _cp_loss(params, tokens, labels, config, cfg, mesh)
            return gpt_mod.loss_fn(params, tokens, labels, config,
                                   mp_constraint=self._mp_constraint,
                                   remat=cfg.remat, moe_impl=moe_impl)

        def step(params, opt_state, tokens, labels):
            loss, grads = jax.value_and_grad(loss_of)(params, tokens, labels)
            if cfg.sharding_stage >= 2 and cfg.zero_axis is not None:
                # ZeRO-2: pin grads to the moment layout so XLA reduce-scatters
                # them over the zero axis instead of all-reducing full grads
                # (ref GroupShardedStage2 reduce-to-owner)
                grads = jax.tree_util.tree_map(
                    lambda g, sh: jax.lax.with_sharding_constraint(g, sh),
                    grads, self._m_shardings)
            if clip is not None:
                gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                                     for g in jax.tree_util.tree_leaves(grads)))
                scale = jnp.minimum(clip / jnp.maximum(gnorm, clip), 1.0)
                grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            stepno = opt_state["step"] + 1
            b1p = 1 - b1 ** stepno.astype(jnp.float32)
            b2p = 1 - b2 ** stepno.astype(jnp.float32)

            mdt = self.moment_dtype

            def upd(p, g, m, v):
                g32 = g.astype(jnp.float32)
                m32 = b1 * m.astype(jnp.float32) + (1 - b1) * g32
                v32 = b2 * v.astype(jnp.float32) + (1 - b2) * g32 * g32
                u = (m32 / b1p) / (jnp.sqrt(v32 / b2p) + 1e-8)
                newp = p.astype(jnp.float32) * (1 - lr * wd) - lr * u
                return newp.astype(p.dtype), m32.astype(mdt), v32.astype(mdt)

            out = jax.tree_util.tree_map(upd, params, grads, opt_state["m"],
                                         opt_state["v"])
            new_params = jax.tree_util.tree_map(lambda t: t[0], out,
                                                is_leaf=lambda x: isinstance(x, tuple))
            new_m = jax.tree_util.tree_map(lambda t: t[1], out,
                                           is_leaf=lambda x: isinstance(x, tuple))
            new_v = jax.tree_util.tree_map(lambda t: t[2], out,
                                           is_leaf=lambda x: isinstance(x, tuple))
            return loss, new_params, {"m": new_m, "v": new_v, "step": stepno}

        # batch splits over dp AND sharding AND ep: the zero group is a
        # data-parallel group with sharded states, and ep ranks each own a batch
        # shard whose tokens they route (ref: moe_group is a data-parallel group)
        batch_axes = ("dp", "sharding", "ep")
        data_sharding = NamedSharding(self.mesh, P(batch_axes, None))
        opt_sh = {"m": self._m_shardings, "v": self._m_shardings, "step": None}
        # out_shardings pinned so params stay in the param layout across steps (else
        # XLA propagates the ZeRO 'dp' shard from the moments onto updated params and
        # the next call's in_shardings check rejects them)
        return jax.jit(step, donate_argnums=(0, 1),
                       in_shardings=(self.param_shardings, opt_sh,
                                     data_sharding, data_sharding),
                       out_shardings=(None, self.param_shardings, opt_sh))

    def shard_batch(self, tokens, labels):
        ds = NamedSharding(self.mesh, P(("dp", "sharding", "ep"), None))
        return (jax.device_put(jnp.asarray(tokens), ds),
                jax.device_put(jnp.asarray(labels), ds))

    def train_step(self, tokens, labels):
        tokens, labels = self.shard_batch(tokens, labels)
        loss, self.params, self.opt_state = self._step_fn(
            self.params, self.opt_state, tokens, labels)
        return loss

    def eval_loss(self, tokens, labels):
        # jitted once with the trainer's param shardings and reused — the old
        # eager loss_fn call retraced the whole model on every eval batch
        if self._eval_fn is None:
            config = self.config
            self._eval_fn = jax.jit(
                lambda p, t, l: gpt_mod.loss_fn(p, t, l, config),
                in_shardings=(self.param_shardings, None, None))
        return self._eval_fn(self.params, jnp.asarray(tokens),
                             jnp.asarray(labels))
