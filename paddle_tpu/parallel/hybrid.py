"""Compiled hybrid-parallel trainer: dp × pp × mp (+ZeRO, +remat) in ONE jitted step.

This is the TPU-native answer to the reference's hybrid stack
(`fleet/meta_parallel/` DP reducer + mpu TP layers + `pipeline_parallel.py` 1F1B +
sharding optimizer):

- **dp / mp**: GSPMD.  Parameters carry NamedShardings (mp = Megatron layout: qkv/fc1
  column-split, proj/fc2 row-split, vocab-split embedding); the batch is sharded over
  dp; XLA inserts the exact allreduce/allgather/reduce-scatter set the reference codes
  by hand in mp_ops.py and the DP reducer — fused into the backward schedule.
- **pp**: a GPipe microbatch loop written with `jax.shard_map(axis_names={'pp'})` +
  `ppermute` inside the SAME jitted program — stages exchange activations over ICI
  each tick; `jax.grad` differentiates through the scan, producing the reverse
  pipeline automatically (the reference's hand-written 1F1B send/recv schedule,
  `pp_utils/p2p_communication.py`, becomes ~30 lines).
- **ZeRO stage-1**: optimizer moments get NamedShardings split over dp
  (`DygraphShardingOptimizer` parity, but it's just a sharding annotation here).
- **sp (sequence parallel)**: activations outside attention are sharded over mp on
  the sequence axis via sharding constraints when `sequence_parallel=True`.
- **remat**: `jax.checkpoint` around each block (`recompute` parity).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import gpt as gpt_mod


@dataclasses.dataclass
class MeshConfig:
    dp: int = 1
    pp: int = 1
    mp: int = 1
    sharding_stage: int = 1      # ZeRO stage for optimizer state (0 = off)
    micro_batches: int = 1       # pipeline microbatches (per global step)
    sequence_parallel: bool = False
    remat: bool = False

    @property
    def size(self):
        return self.dp * self.pp * self.mp


def build_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    devs = np.array(devices if devices is not None else jax.devices()[:cfg.size])
    assert devs.size >= cfg.size, f"need {cfg.size} devices, have {devs.size}"
    return Mesh(devs[:cfg.size].reshape(cfg.dp, cfg.pp, cfg.mp), ("dp", "pp", "mp"))


# ---------------------------------------------------------------------------
# sharding rules for the GPT params pytree (Megatron layout)
# ---------------------------------------------------------------------------

def gpt_param_specs(cfg: MeshConfig):
    pp = "pp" if cfg.pp > 1 else None
    mp = "mp" if cfg.mp > 1 else None
    blocks = {
        "ln1_w": P(pp, None), "ln1_b": P(pp, None),
        "qkv_w": P(pp, None, mp), "qkv_b": P(pp, mp),
        "proj_w": P(pp, mp, None), "proj_b": P(pp, None),
        "ln2_w": P(pp, None), "ln2_b": P(pp, None),
        "fc1_w": P(pp, None, mp), "fc1_b": P(pp, mp),
        "fc2_w": P(pp, mp, None), "fc2_b": P(pp, None),
    }
    specs = {
        "wte": P(mp, None),
        "blocks": blocks,
        "lnf_w": P(None), "lnf_b": P(None),
    }
    return specs


def _opt_state_spec(param_spec: P, shape, cfg: MeshConfig):
    """ZeRO-1: additionally shard optimizer moments over dp on the first axis that is
    unsharded and divisible."""
    if cfg.sharding_stage < 1 or cfg.dp == 1:
        return param_spec
    spec = list(param_spec) + [None] * (len(shape) - len(param_spec))
    for i, (s, cur) in enumerate(zip(shape, spec)):
        if cur is None and s % cfg.dp == 0 and s >= cfg.dp:
            spec[i] = "dp"
            break
    return P(*spec)


# ---------------------------------------------------------------------------
# pipeline loop (manual over 'pp', GSPMD over dp/mp)
# ---------------------------------------------------------------------------

def _pp_loss(params, tokens, labels, config, cfg: MeshConfig, mesh):
    """GPipe loss under shard_map over 'pp'.  blocks param leading axis is
    pp-sharded; embed/head replicated across pp."""
    assert config.use_rope, "pipeline path requires rope (no wpe broadcast across stages)"
    assert config.tie_word_embeddings, \
        "pipeline path computes the head from the tied embedding; untied lm_head " \
        "across stages is not wired yet"
    M = cfg.micro_batches
    Ppp = cfg.pp

    def local_fn(blocks_local, wte, lnf_w, lnf_b, tok_mb, lab_mb):
        # blocks_local: [L/Ppp, ...]; tok_mb/lab_mb: [M, mb, S]
        p = jax.lax.axis_index("pp")
        T = M + Ppp - 1
        mb, S = tok_mb.shape[1], tok_mb.shape[2]
        D = config.hidden_size

        def embed(t):
            ids = tok_mb[jnp.clip(t, 0, M - 1)]
            return jnp.take(wte, ids, axis=0)

        def tick(buf, t):
            inp = jnp.where(p == 0, embed(t), buf)
            out = gpt_mod.run_blocks(blocks_local, inp, config, remat=cfg.remat)
            nxt = jax.lax.ppermute(out, "pp",
                                   [(i, (i + 1) % Ppp) for i in range(Ppp)])
            # last stage finalizes microbatch t-(Ppp-1)
            midx = jnp.clip(t - (Ppp - 1), 0, M - 1)
            h = gpt_mod._norm(out, lnf_w, lnf_b, config)
            logits = jnp.matmul(h, wte.T)
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            lab = lab_mb[midx]
            safe = jnp.where(lab < 0, 0, lab)
            picked = jnp.take_along_axis(lp, safe[..., None], axis=-1)[..., 0]
            mask = (lab >= 0).astype(jnp.float32)
            valid = ((p == Ppp - 1) & (t >= Ppp - 1) & (t < M + Ppp - 1)) \
                .astype(jnp.float32)
            # accumulate global sums so normalization matches the non-pp loss even
            # with unevenly masked microbatches
            return nxt, (-jnp.sum(picked * mask) * valid, jnp.sum(mask) * valid)

        buf0 = jax.lax.pvary(jnp.zeros((mb, S, D), wte.dtype), ("pp",))
        _, (loss_sums, mask_sums) = jax.lax.scan(tick, buf0, jnp.arange(T))
        total = jnp.sum(loss_sums) / jnp.maximum(jnp.sum(mask_sums), 1.0)
        # only the last stage holds the loss; share it
        return jax.lax.psum(total, "pp")

    blocks = params["blocks"]
    f = jax.shard_map(
        local_fn, mesh=mesh, axis_names={"pp"},
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), blocks),
                  P(), P(), P(), P(), P()),
        out_specs=P(),
    )
    B = tokens.shape[0]
    mb = B // M
    tok_mb = tokens.reshape(M, mb, -1)
    lab_mb = labels.reshape(M, mb, -1)
    return f(blocks, params["wte"], params["lnf_w"], params["lnf_b"], tok_mb, lab_mb)


# ---------------------------------------------------------------------------
# trainer
# ---------------------------------------------------------------------------

class HybridParallelTrainer:
    """Owns mesh + sharded params/opt-state + the ONE jitted train step."""

    def __init__(self, config: gpt_mod.GPTConfig, mesh_cfg: MeshConfig,
                 learning_rate=1e-4, weight_decay=0.01, beta1=0.9, beta2=0.95,
                 grad_clip_norm: Optional[float] = 1.0, seed=0, devices=None,
                 moment_dtype=jnp.float32):
        self.config = config
        self.cfg = mesh_cfg
        self.mesh = build_mesh(mesh_cfg, devices)
        self.lr = learning_rate
        self.wd = weight_decay
        self.betas = (beta1, beta2)
        self.clip_norm = grad_clip_norm
        self.moment_dtype = moment_dtype

        specs = gpt_param_specs(mesh_cfg)
        if not config.use_rope:
            specs["wpe"] = P(None, None)
        if not config.tie_word_embeddings:
            specs["lm_head"] = P(None, "mp" if mesh_cfg.mp > 1 else None)
        self.param_specs = specs
        self.param_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))

        key = jax.random.key(seed)
        init = jax.jit(functools.partial(gpt_mod.init_params, config),
                       out_shardings=self.param_shardings)
        self.params = init(key)

        m_shardings = jax.tree_util.tree_map(
            lambda l, s: NamedSharding(self.mesh, _opt_state_spec(s, l.shape, mesh_cfg)),
            self.params, specs)
        self._m_shardings = m_shardings
        mdt = moment_dtype
        init_opt = jax.jit(
            lambda p: {"m": jax.tree_util.tree_map(lambda x: jnp.zeros_like(x, mdt), p),
                       "v": jax.tree_util.tree_map(lambda x: jnp.zeros_like(x, mdt), p),
                       "step": jnp.zeros((), jnp.int32)},
            out_shardings={"m": m_shardings, "v": m_shardings, "step": None})
        self.opt_state = init_opt(self.params)
        self._step_fn = self._build_step()

    # ---- sharding constraint hook handed to the model ----
    def _mp_constraint(self, x, kind):
        cfg = self.cfg
        if cfg.mp <= 1:
            return x
        if kind in ("hidden_mp", "ffn_mp"):
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(self.mesh, P("dp", None, "mp")))
        if kind == "act" and cfg.sequence_parallel:
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(self.mesh, P("dp", "mp", None)))
        return x

    def _build_step(self):
        config = self.config
        cfg = self.cfg
        mesh = self.mesh
        lr, wd = self.lr, self.wd
        b1, b2 = self.betas
        clip = self.clip_norm

        def loss_of(params, tokens, labels):
            if cfg.pp > 1:
                return _pp_loss(params, tokens, labels, config, cfg, mesh)
            return gpt_mod.loss_fn(params, tokens, labels, config,
                                   mp_constraint=self._mp_constraint,
                                   remat=cfg.remat)

        def step(params, opt_state, tokens, labels):
            loss, grads = jax.value_and_grad(loss_of)(params, tokens, labels)
            if clip is not None:
                gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                                     for g in jax.tree_util.tree_leaves(grads)))
                scale = jnp.minimum(clip / jnp.maximum(gnorm, clip), 1.0)
                grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            stepno = opt_state["step"] + 1
            b1p = 1 - b1 ** stepno.astype(jnp.float32)
            b2p = 1 - b2 ** stepno.astype(jnp.float32)

            mdt = self.moment_dtype

            def upd(p, g, m, v):
                g32 = g.astype(jnp.float32)
                m32 = b1 * m.astype(jnp.float32) + (1 - b1) * g32
                v32 = b2 * v.astype(jnp.float32) + (1 - b2) * g32 * g32
                u = (m32 / b1p) / (jnp.sqrt(v32 / b2p) + 1e-8)
                newp = p.astype(jnp.float32) * (1 - lr * wd) - lr * u
                return newp.astype(p.dtype), m32.astype(mdt), v32.astype(mdt)

            out = jax.tree_util.tree_map(upd, params, grads, opt_state["m"],
                                         opt_state["v"])
            new_params = jax.tree_util.tree_map(lambda t: t[0], out,
                                                is_leaf=lambda x: isinstance(x, tuple))
            new_m = jax.tree_util.tree_map(lambda t: t[1], out,
                                           is_leaf=lambda x: isinstance(x, tuple))
            new_v = jax.tree_util.tree_map(lambda t: t[2], out,
                                           is_leaf=lambda x: isinstance(x, tuple))
            return loss, new_params, {"m": new_m, "v": new_v, "step": stepno}

        data_sharding = NamedSharding(self.mesh, P("dp", None))
        opt_sh = {"m": self._m_shardings, "v": self._m_shardings, "step": None}
        # out_shardings pinned so params stay in the param layout across steps (else
        # XLA propagates the ZeRO 'dp' shard from the moments onto updated params and
        # the next call's in_shardings check rejects them)
        return jax.jit(step, donate_argnums=(0, 1),
                       in_shardings=(self.param_shardings, opt_sh,
                                     data_sharding, data_sharding),
                       out_shardings=(None, self.param_shardings, opt_sh))

    def shard_batch(self, tokens, labels):
        ds = NamedSharding(self.mesh, P("dp", None))
        return (jax.device_put(jnp.asarray(tokens), ds),
                jax.device_put(jnp.asarray(labels), ds))

    def train_step(self, tokens, labels):
        tokens, labels = self.shard_batch(tokens, labels)
        loss, self.params, self.opt_state = self._step_fn(
            self.params, self.opt_state, tokens, labels)
        return loss

    def eval_loss(self, tokens, labels):
        return gpt_mod.loss_fn(self.params, jnp.asarray(tokens), jnp.asarray(labels),
                               self.config)
