"""Ring attention — context parallelism for long sequences.

Beyond-reference capability (SURVEY §7.10): the reference's long-context story
is flash-attn + Megatron SP + recompute; ring/blockwise attention (Liu et al.
2023) is the idiomatic TPU mechanism: shard the SEQUENCE over a `cp` mesh axis,
keep q local, and rotate k/v shards around the ring with `ppermute` while
accumulating blockwise-softmax partial results — attention memory per chip
drops from O(S^2) to O((S/cp)^2) and the k/v transfer overlaps with compute on
ICI.

Design: the chunk loop is a `lax.scan` whose carry holds the circulating k/v
chunk and the online-softmax state (o, m, l).  `jax.grad` differentiates
through the scan and transposes each `ppermute` into the reverse-ring permute,
yielding the standard ring-attention backward (dk/dv circulate backwards)
without a hand-written schedule.  Each chunk's blockwise compute is
`jax.checkpoint`ed so backward memory stays at one chunk of logits.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def shard_map_compat(f, *, mesh, axis_names, in_specs, out_specs):
    """`jax.shard_map` (axis_names=manual axes) with a fallback to
    `jax.experimental.shard_map` on older JAX.  The fallback goes FULL manual
    (all mesh axes) rather than `auto=<complement>`: partial-manual regions on
    old jaxlib hit an SPMD-partitioner CHECK crash (IsManualSubgroup mismatch)
    and subtle replication bugs.  Axes absent from the specs then just compute
    redundantly per rank — semantically identical, and only the new-JAX path
    runs at scale."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, axis_names=set(axis_names),
                             in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map
    return shard_map(f, mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=False)


def ring_attention_local(q, k, v, axis_name: str, cp: int, causal: bool = True,
                         scale=None):
    """Runs INSIDE a manual region over `axis_name` (cp ranks).

    q, k, v: [B, S_local, H, D] — this rank's sequence shard (global sequence
    order follows rank order).  Returns [B, S_local, H, D].
    """
    B, Sl, H, D = q.shape
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    r = jax.lax.axis_index(axis_name)
    qpos = r * Sl + jnp.arange(Sl)

    qt = jnp.transpose(q, (0, 2, 1, 3))                 # [B, H, Sl, D]

    def blockwise(qt_, kc, vc, o, m, l, kpos):
        """One k/v chunk folded into the online-softmax state."""
        sblk = jnp.einsum("bhqd,bkhd->bhqk", qt_, kc,
                          preferred_element_type=jnp.float32) * s
        if causal:
            mask = qpos[:, None] >= kpos[None, :]
            sblk = jnp.where(mask[None, None], sblk, NEG_INF)
        m_cur = jnp.max(sblk, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(sblk - m_new[..., None])
        if causal:
            # fully-masked rows: exp(NEG-NEG)=1 must not leak mass
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(vc.dtype), vc,
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    blockwise = jax.checkpoint(blockwise)

    def step(carry, t):
        kc, vc, o, m, l = carry
        src = (r - t) % cp                              # chunk's origin rank
        kpos = src * Sl + jnp.arange(Sl)
        o, m, l = blockwise(qt, kc, vc, o, m, l, kpos)
        # rotate the k/v chunk one step around the ring
        perm = [(i, (i + 1) % cp) for i in range(cp)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (kc, vc, o, m, l), None

    from ..models.gpt import pvary_compat
    vma = (tuple(getattr(jax.typeof(q), "vma", (axis_name,))) or (axis_name,)) \
        if hasattr(jax, "typeof") else (axis_name,)
    o0 = pvary_compat(jnp.zeros((B, H, Sl, D), jnp.float32), vma)
    m0 = pvary_compat(jnp.full((B, H, Sl), NEG_INF, jnp.float32), vma)
    l0 = pvary_compat(jnp.zeros((B, H, Sl), jnp.float32), vma)

    (kf, vf, o, m, l), _ = jax.lax.scan(step, (k, v, o0, m0, l0),
                                        jnp.arange(cp))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.transpose(out.astype(q.dtype), (0, 2, 1, 3))
    # named so remat_policy_save_attention saves the ring output: block replay
    # under cfg.remat must not re-run the cp-step scan + ppermutes
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(out, "flash_out")


def ring_attention(q, k, v, mesh, axis_name: str = "cp", causal: bool = True,
                   scale=None):
    """GSPMD entry: q, k, v [B, S, H, D] with S sharded over `axis_name`."""
    cp = mesh.shape[axis_name]
    fn = functools.partial(ring_attention_local, axis_name=axis_name, cp=cp,
                           causal=causal, scale=scale)
    spec = P(None, axis_name, None, None)
    return shard_map_compat(lambda a, b, c: fn(a, b, c), mesh=mesh,
                            axis_names={axis_name},
                            in_specs=(spec, spec, spec),
                            out_specs=spec)(q, k, v)
