from .hybrid import (HybridParallelTrainer, MeshConfig,  # noqa
                     serving_mesh, serving_param_specs)
