from .hybrid import HybridParallelTrainer, MeshConfig  # noqa
