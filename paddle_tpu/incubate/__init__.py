from . import distributed  # noqa
from . import nn  # noqa
from .nn import functional  # noqa


def autotune(config=None):
    pass

from .segment_ops import (graph_send_recv, identity_loss, segment_max,  # noqa
                          segment_mean, segment_min, segment_sum)
from .optimizer import LookAhead, ModelAverage  # noqa
from ..nn.functional.sparse_ops import (softmax_mask_fuse,  # noqa
                                        softmax_mask_fuse_upper_triangle)


def graph_khop_sampler(*args, **kwargs):
    raise NotImplementedError(
        "graph_khop_sampler: dynamic-shape neighbor sampling is host-side; see "
        "paddle_tpu.geometric for the TPU-native message-passing path")


def graph_sample_neighbors(*args, **kwargs):
    raise NotImplementedError("see paddle_tpu.geometric sampling note")


def graph_reindex(*args, **kwargs):
    raise NotImplementedError("see paddle_tpu.geometric sampling note")

from .custom_op import custom_op_from_c, get_custom_op, register_custom_op  # noqa
