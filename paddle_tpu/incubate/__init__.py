from . import distributed  # noqa
from . import nn  # noqa
from .nn import functional  # noqa


def autotune(config=None):
    pass
