"""incubate optimizers: LookAhead, ModelAverage.

Reference parity: `python/paddle/incubate/optimizer/lookahead.py`,
`modelaverage.py`.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..optimizer.optimizer import Optimizer


class LookAhead(Optimizer):
    """k steps forward, 1 step back (Zhang et al. 2019; ref lookahead.py)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._step_count = 0
        self._parameter_list = inner_optimizer._parameter_list
        # slow weights snapshot the INITIAL params (ref lookahead.py) — seeding
        # lazily from already-updated fast weights would no-op the first sync
        self._slow = {id(p): p._data for p in self._parameter_list}

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            for p in self._parameter_list:
                pid = id(p)
                slow = self._slow[pid] + self.alpha * (p._data - self._slow[pid])
                self._slow[pid] = slow
                p._data = slow

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def state_dict(self):
        return {"inner": self.inner_optimizer.state_dict(),
                "step": self._step_count}

    def set_state_dict(self, state):
        self.inner_optimizer.set_state_dict(state.get("inner", {}))
        self._step_count = state.get("step", 0)


class ModelAverage(Optimizer):
    """Running average of parameters for eval (ref modelaverage.py)."""

    def __init__(self, average_window_rate, parameters=None, min_average_window=10000,
                 max_average_window=10000, name=None):
        self._parameter_list = list(parameters or [])
        self.avg = {id(p): p._data for p in self._parameter_list}
        self.n = 0
        self._backup = None

    def step(self):
        self.n += 1
        for p in self._parameter_list:
            pid = id(p)
            self.avg[pid] = self.avg[pid] + (p._data - self.avg[pid]) / self.n

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def guard():
            backup = {id(p): p._data for p in self._parameter_list}
            for p in self._parameter_list:
                p._data = self.avg[id(p)]
            try:
                yield
            finally:
                if need_restore:
                    for p in self._parameter_list:
                        p._data = backup[id(p)]
        return guard()

    def restore(self, executor=None):
        pass

    def clear_grad(self, set_to_zero=True):
        pass
