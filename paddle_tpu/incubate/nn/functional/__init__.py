"""Fused ops (reference: `python/paddle/incubate/nn/functional/` — fused_matmul_bias,
fused_rotary_position_embedding, fused_layer_norm, fused_rms_norm, fused_dropout_add,
fused attention family; CUDA kernels in `phi/kernels/fusion/gpu/`).

TPU-native: the hot kernels (flash attention, rms norm) have Pallas implementations in
`paddle_tpu/incubate/kernels/`; the rest are written as single jnp expressions that XLA
fuses into one kernel — on TPU that IS the fused implementation.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ....core import generator as _gen
from ....core.tensor import Tensor, apply


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False, name=None):
    def f(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jnp.matmul(a, b)
        if rest:
            out = out + rest[0]
        return out
    args = (x, y) + ((bias,) if bias is not None else ())
    return apply("fused_matmul_bias", f, *args)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, False, transpose_weight)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False, activation="gelu"):
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    from ....nn import functional as F
    return getattr(F, activation)(out)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1,
                   bias=None, residual=None, quant_scale=-1, name=None):
    """RMSNorm — routes to the Pallas kernel on TPU (reference
    `fused_rms_norm_kernel.cu`)."""
    from ...kernels.rms_norm import rms_norm_fused

    def f(a, w, *rest):
        it = iter(rest)
        res = next(it) if residual is not None else None
        b = next(it) if norm_bias is not None else None
        if res is not None:
            a = a + res
        out = rms_norm_fused(a, w, epsilon)
        if b is not None:
            out = out + b
        return (out, a) if res is not None else out
    args = [x, norm_weight]
    if residual is not None:
        args.append(residual)
    if norm_bias is not None:
        args.append(norm_bias)
    return apply("fused_rms_norm", f, *args)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, begin_norm_axis=-1,
                     bias=None, residual=None, quant_scale=-1, name=None):
    def f(a, w, b, *rest):
        if rest:
            a = a + rest[0]
        mu = jnp.mean(a.astype(jnp.float32), axis=-1, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=-1, keepdims=True)
        out = ((a.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + epsilon))
        out = (out * w + b).astype(a.dtype)
        return (out, a) if rest else out
    args = [x, norm_weight, norm_bias]
    if residual is not None:
        args.append(residual)
    return apply("fused_layer_norm", f, *args)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      fixed_seed_offset=None, rng_name="", name=None):
    if not training or p == 0.0:
        return apply("fused_dropout_add", jnp.add, x, y)

    def f(a, b):
        keep = jax.random.bernoulli(_gen.next_key(), 1.0 - p, a.shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype) + b
        return jnp.where(keep, a, 0.0).astype(a.dtype) + b
    return apply("fused_dropout_add", f, x, y)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None, ln_scale=None,
                                           ln_bias=None, dropout_rate=0.5,
                                           ln_epsilon=1e-5, training=True, mode=
                                           "upscale_in_train", name=None):
    out = x if bias is None else x + bias
    out = fused_dropout_add(out, residual, dropout_rate, training, mode)
    from ....nn import functional as F
    return F.layer_norm(out, out.shape[-1], ln_scale, ln_bias, ln_epsilon)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0,
                                    name=None):
    """RoPE (reference `fused_rope_kernel.cu`).  Layout [B, S, H, D]."""
    from ...kernels.rope import apply_rope

    outs = []
    tensors = [t for t in (q, k, v) if t is not None]

    def build(sin_d, cos_d, a):
        return apply_rope(a, sin_d, cos_d, use_neox_rotary_style)

    S = q.shape[1] if not time_major else q.shape[0]
    D = q.shape[-1]
    if sin is None:
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
        t_idx = jnp.arange(S, dtype=jnp.float32)
        freqs = jnp.outer(t_idx, inv)
        sin_d = jnp.sin(freqs)
        cos_d = jnp.cos(freqs)
    else:
        sin_d = sin._data if isinstance(sin, Tensor) else jnp.asarray(sin)
        cos_d = cos._data if isinstance(cos, Tensor) else jnp.asarray(cos)
        # accept [1, S, 1, D] paddle layout; squeeze to [S, D/2]
        sin_d = sin_d.reshape(S, -1)
        cos_d = cos_d.reshape(S, -1)
        if sin_d.shape[-1] == D:
            sin_d = sin_d[:, : D // 2] if use_neox_rotary_style else sin_d[:, ::2]
            cos_d = cos_d[:, : D // 2] if use_neox_rotary_style else cos_d[:, ::2]
    if position_ids is not None:
        pid = position_ids._data if isinstance(position_ids, Tensor) else jnp.asarray(position_ids)
        sin_d = jnp.take(sin_d, pid.astype(jnp.int32), axis=0)
        cos_d = jnp.take(cos_d, pid.astype(jnp.int32), axis=0)

    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        outs.append(apply("fused_rope", lambda a: build(sin_d, cos_d, a), t))
    return tuple(outs)


def fused_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                is_causal=False, training=True, scaling_factor=None,
                                name=None):
    """Fused SDPA [B, S, H, D] — Pallas flash attention on TPU, XLA path elsewhere
    (reference `fused_dot_product_attention` / flash_attn)."""
    from ...kernels.flash_attention import flash_attention_fused

    def f(qq, kk, vv, *rest):
        mask = rest[0] if rest else None
        return flash_attention_fused(qq, kk, vv, mask=mask, causal=is_causal,
                                     scale=scaling_factor,
                                     dropout_p=dropout_p if training else 0.0)
    args = (q, k, v) + ((attn_mask,) if attn_mask is not None else ())
    return apply("fused_dot_product_attention", f, *args)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               transpose_qkv_wb=False, name=None):
    from ....nn import functional as F
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1], pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    qkv = fused_matmul_bias(x, qkv_weight, qkv_bias)
    B, S = x.shape[0], x.shape[1]
    d_model = x.shape[-1]
    if num_heads is None:
        raise ValueError("num_heads required")
    head_dim = d_model // num_heads
    qkv = qkv.reshape([B, S, 3, num_heads, head_dim])
    from ....ops.manipulation import split, squeeze
    q, k, v = [squeeze(t, 2) for t in split(qkv, 3, axis=2)]
    out = fused_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                      dropout_p=attn_dropout_rate if training else 0.0)
    out = out.reshape([B, S, d_model])
    out = fused_matmul_bias(out, linear_weight, linear_bias)
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1], ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None, ln2_scale=None,
                      ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5,
                      activation="relu", ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode="upscale_in_train",
                      ring_id=-1, name=None):
    from ....nn import functional as F
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1], ln1_scale, ln1_bias, ln1_epsilon)
    out = fused_matmul_bias(x, linear1_weight, linear1_bias)
    out = getattr(F, activation)(out)
    out = F.dropout(out, dropout1_rate, training=training, mode=mode)
    out = fused_matmul_bias(out, linear2_weight, linear2_bias)
    out = F.dropout(out, dropout2_rate, training=training, mode=mode)
    out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1], ln2_scale, ln2_bias, ln2_epsilon)
    return out


def swiglu(x, y=None, name=None):
    if y is not None:
        return apply("swiglu", lambda a, b: jax.nn.silu(a) * b, x, y)

    def f(a):
        u, g = jnp.split(a, 2, axis=-1)
        return jax.nn.silu(u) * g
    return apply("swiglu", f, x)


def variable_length_memory_efficient_attention(query, key, value, seq_lens=None,
                                               kv_seq_lens=None, mask=None, scale=None,
                                               causal=False, pre_cache_length=0):
    return fused_dot_product_attention(query, key, value, attn_mask=mask,
                                       is_causal=causal, scaling_factor=scale)
