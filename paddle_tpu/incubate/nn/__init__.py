from . import functional  # noqa
from .layer import FusedLinear, FusedMultiHeadAttention, FusedTransformerEncoderLayer  # noqa
