"""Fused layers (reference: `python/paddle/incubate/nn/layer/` — FusedLinear,
FusedMultiHeadAttention, FusedTransformerEncoderLayer)."""
from __future__ import annotations

from ...nn.initializer import XavierNormal
from ...nn.layer.layers import Layer
from . import functional as IF


class FusedLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 transpose_weight=False, name=None):
        super().__init__()
        self._transpose = transpose_weight
        shape = [out_features, in_features] if transpose_weight else [in_features, out_features]
        self.weight = self.create_parameter(shape, weight_attr,
                                            default_initializer=XavierNormal())
        self.bias = self.create_parameter([out_features], bias_attr, is_bias=True)

    def forward(self, x):
        return IF.fused_linear(x, self.weight, self.bias, self._transpose)


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5,
                 kdim=None, vdim=None, normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5, nranks=1,
                 ring_id=-1, name=None):
        super().__init__()
        from ...nn.initializer import Constant
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self.qkv_weight = self.create_parameter([embed_dim, 3 * embed_dim],
                                                qkv_weight_attr,
                                                default_initializer=XavierNormal())
        self.qkv_bias = self.create_parameter([3 * embed_dim], qkv_bias_attr,
                                              is_bias=True)
        self.linear_weight = self.create_parameter([embed_dim, embed_dim],
                                                   linear_weight_attr,
                                                   default_initializer=XavierNormal())
        self.linear_bias = self.create_parameter([embed_dim], linear_bias_attr,
                                                 is_bias=True)
        self.pre_ln_scale = self.create_parameter([embed_dim], pre_ln_scale_attr,
                                                  default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter([embed_dim], pre_ln_bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter([embed_dim], ln_scale_attr,
                                              default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], ln_bias_attr, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        return IF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before, pre_ln_scale=self.pre_ln_scale,
            pre_ln_bias=self.pre_ln_bias, ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias, attn_mask=attn_mask,
            dropout_rate=self.dropout_rate, attn_dropout_rate=self.attn_dropout_rate,
            training=self.training, num_heads=self.num_heads)


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout_rate = dropout_rate if attn_dropout_rate is None else attn_dropout_rate
        act_dropout_rate = dropout_rate if act_dropout_rate is None else act_dropout_rate
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate, normalize_before=normalize_before)
        from ...nn.initializer import Constant
        self.activation = activation
        self.normalize_before = normalize_before
        self.dropout1 = dropout_rate
        self.act_dropout = act_dropout_rate
        self.linear1_weight = self.create_parameter([d_model, dim_feedforward],
                                                    weight_attr,
                                                    default_initializer=XavierNormal())
        self.linear1_bias = self.create_parameter([dim_feedforward], bias_attr,
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter([dim_feedforward, d_model],
                                                    weight_attr,
                                                    default_initializer=XavierNormal())
        self.linear2_bias = self.create_parameter([d_model], bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter([d_model], None,
                                               default_initializer=Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], None, is_bias=True)
        self.ln2_scale = self.create_parameter([d_model], None,
                                               default_initializer=Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], None, is_bias=True)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return IF.fused_feedforward(
            out, self.linear1_weight, self.linear2_weight, self.linear1_bias,
            self.linear2_bias, self.ln1_scale, self.ln1_bias, self.ln2_scale,
            self.ln2_bias, self.dropout1, self.act_dropout, self.activation,
            pre_layer_norm=self.normalize_before, training=self.training)
