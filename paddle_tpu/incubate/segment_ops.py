"""Segment reductions + graph message passing.

Reference parity: `python/paddle/incubate/__init__.py` segment_sum/mean/max/min
(`phi/kernels/segment_pool_kernel.*`) and `graph_send_recv`
(`phi/kernels/graph_send_recv_kernel.*`).  TPU-native: jax.ops.segment_* are
XLA scatter-reductions — one fused kernel, no atomics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import apply


def segment_sum(data, segment_ids, name=None):
    return apply("segment_sum",
                 lambda d, i: jax.ops.segment_sum(d, i.astype(jnp.int32)),
                 data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    def f(d, i):
        i = i.astype(jnp.int32)
        s = jax.ops.segment_sum(d, i)
        cnt = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), i)
        return s / jnp.maximum(cnt, 1.0).reshape((-1,) + (1,) * (d.ndim - 1))
    return apply("segment_mean", f, data, segment_ids)


def segment_max(data, segment_ids, name=None):
    return apply("segment_max",
                 lambda d, i: jax.ops.segment_max(d, i.astype(jnp.int32)),
                 data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return apply("segment_min",
                 lambda d, i: jax.ops.segment_min(d, i.astype(jnp.int32)),
                 data, segment_ids)


def graph_send_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                    name=None):
    """ref graph_send_recv: gather x[src], reduce into dst buckets."""
    red = {"sum": jax.ops.segment_sum, "mean": None, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}[reduce_op]

    def f(a, si, di):
        msgs = a[si.astype(jnp.int32)]
        n = out_size or a.shape[0]
        di32 = di.astype(jnp.int32)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, di32, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), a.dtype), di32,
                                      num_segments=n)
            return s / jnp.maximum(cnt, 1.0).reshape((-1,) + (1,) * (a.ndim - 1))
        return red(msgs, di32, num_segments=n)
    return apply("graph_send_recv", f, x, src_index, dst_index)


def identity_loss(x, reduction="none"):
    """ref incubate identity_loss (IPU custom-loss marker)."""
    if reduction in (0, "sum"):
        return x.sum()
    if reduction in (1, "mean"):
        return x.mean()
    return x
