"""MoELayer (reference: `incubate/distributed/models/moe/moe_layer.py` — capacity-based
dispatch via `global_scatter`/`global_gather` alltoall ops).

TPU-native: dispatch is a dense einsum against a one-hot capacity-slotted combine
tensor (the GShard formulation) — static shapes, MXU-friendly, and under the hybrid
trainer the expert dimension shards over the mesh's expert axis so XLA lowers the
dispatch/combine einsums to the same all-to-all the reference codes by hand.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor, apply
from .....nn.layer.layers import Layer
from .gate import GShardGate, NaiveGate, SwitchGate


def dispatch_and_combine(x, gate_idx, gate_val, experts_fn, num_expert, capacity):
    """Functional GShard dispatch: x [T, D]; gate_idx [T, k]; gate_val [T, k]."""
    T, D = x.shape
    k = gate_idx.shape[1]
    E, C = num_expert, capacity

    onehot = jax.nn.one_hot(gate_idx.astype(jnp.int32), E, dtype=jnp.float32)  # [T,k,E]
    # position of each token within its expert queue
    pos = jnp.cumsum(onehot.reshape(T * k, E), axis=0).reshape(T, k, E) * onehot - 1.0
    keep = (pos < C) & (onehot > 0)
    pos = jnp.clip(pos, 0, C - 1).astype(jnp.int32)
    # combine weights [T, k, E, C]
    capslot = jax.nn.one_hot(pos, C, dtype=jnp.float32) * keep[..., None]
    combine = jnp.einsum("tk,tkec->tec", gate_val.astype(jnp.float32), capslot)
    dispatch = (combine > 0).astype(x.dtype)  # [T, E, C]
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)  # [E, C, D]
    expert_out = experts_fn(expert_in)  # [E, C, D]
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
    return out


class MoELayer(Layer):
    """(reference MoELayer): gate + per-rank experts + alltoall dispatch.

    `experts` is a list of Layers, each mapping [*, D] -> [*, D].
    """

    def __init__(self, d_model, experts=None, gate=None, moe_group=None, mp_group=None,
                 recompute_interval=0, capacity_factor=1.2, topk=2, **kwargs):
        super().__init__()
        from .....nn.layer.container import LayerList
        self.d_model = d_model
        self.experts = experts if isinstance(experts, LayerList) else LayerList(experts)
        self.num_expert = len(self.experts)
        self.capacity_factor = capacity_factor
        if gate is None or gate == "naive":
            gate = NaiveGate(d_model, self.num_expert, topk=topk)
        elif gate == "gshard":
            gate = GShardGate(d_model, self.num_expert, topk=topk)
        elif gate == "switch":
            gate = SwitchGate(d_model, self.num_expert)
        self.gate = gate

    def forward(self, x):
        orig_shape = x.shape
        x2 = x.reshape([-1, self.d_model])
        T = x2.shape[0]
        gate_idx, gate_val = self.gate(x2)
        C = max(int(self.capacity_factor * T * self.gate.topk / self.num_expert), 4)
        out = self._forward_eager(x2, gate_idx, gate_val, C)
        return out.reshape(orig_shape)

    def _forward_eager(self, x2, gate_idx, gate_val, C):
        from .....ops.creation import zeros
        from .....ops.manipulation import concat
        E = self.num_expert
        T = x2.shape[0]
        k = gate_idx.shape[1]

        def build_combine(idx, val):
            onehot = jax.nn.one_hot(idx.astype(jnp.int32), E, dtype=jnp.float32)
            pos = jnp.cumsum(onehot.reshape(T * k, E), axis=0).reshape(T, k, E) * onehot - 1.0
            keep = (pos < C) & (onehot > 0)
            posc = jnp.clip(pos, 0, C - 1).astype(jnp.int32)
            capslot = jax.nn.one_hot(posc, C, dtype=jnp.float32) * keep[..., None]
            return jnp.einsum("tk,tkec->tec", val.astype(jnp.float32), capslot)

        combine = apply("moe_combine", build_combine, gate_idx, gate_val)
        dispatch = apply("moe_dispatch", lambda c: (c > 0).astype(x2._data.dtype),
                         combine)
        expert_in = apply("moe_scatter", lambda d, xx: jnp.einsum("tec,td->ecd", d, xx),
                          dispatch, x2)
        outs = []
        for e, expert in enumerate(self.experts):
            outs.append(expert(expert_in[e]))
        from .....ops.manipulation import stack
        expert_out = stack(outs, axis=0)
        out = apply("moe_gather",
                    lambda c, eo: jnp.einsum("tec,ecd->td", c.astype(eo.dtype), eo),
                    combine, expert_out)
        return out
