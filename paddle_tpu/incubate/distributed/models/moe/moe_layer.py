"""MoELayer (reference: `incubate/distributed/models/moe/moe_layer.py` — capacity-based
dispatch via `global_scatter`/`global_gather` alltoall ops).

TPU-native: dispatch is a dense einsum against a one-hot capacity-slotted combine
tensor (the GShard formulation) — static shapes, MXU-friendly, and under the hybrid
trainer the expert dimension shards over the mesh's expert axis so XLA lowers the
dispatch/combine einsums to the same all-to-all the reference codes by hand.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor, apply
from .....nn.layer.layers import Layer
from .gate import GShardGate, NaiveGate, SwitchGate


def dispatch_and_combine(x, gate_idx, gate_val, experts_fn, num_expert, capacity):
    """Functional GShard dispatch: x [T, D]; gate_idx [T, k]; gate_val [T, k].

    Slot-scatter formulation (see `dispatch.py`) — no `[T, k, E, C]` combine
    tensor is materialized."""
    from .dispatch import capacity_slots, combine, dispatch
    slot, keep = capacity_slots(gate_idx.astype(jnp.int32), num_expert, capacity)
    expert_in = dispatch(x, slot, num_expert, capacity)  # [E, C, D]
    expert_out = experts_fn(expert_in)                   # [E, C, D]
    return combine(expert_out, slot, keep, gate_val.astype(jnp.float32))


class MoELayer(Layer):
    """(reference MoELayer): gate + per-rank experts + alltoall dispatch.

    `experts` is a list of Layers, each mapping [*, D] -> [*, D].
    """

    def __init__(self, d_model, experts=None, gate=None, moe_group=None, mp_group=None,
                 recompute_interval=0, capacity_factor=1.2, topk=2, **kwargs):
        super().__init__()
        from .....nn.layer.container import LayerList
        self.d_model = d_model
        self.experts = experts if isinstance(experts, LayerList) else LayerList(experts)
        self.num_expert = len(self.experts)
        self.capacity_factor = capacity_factor
        if gate is None or gate == "naive":
            gate = NaiveGate(d_model, self.num_expert, topk=topk)
        elif gate == "gshard":
            gate = GShardGate(d_model, self.num_expert, topk=topk)
        elif gate == "switch":
            gate = SwitchGate(d_model, self.num_expert)
        self.gate = gate

    def forward(self, x):
        orig_shape = x.shape
        x2 = x.reshape([-1, self.d_model])
        T = x2.shape[0]
        gate_idx, gate_val = self.gate(x2)
        C = max(int(self.capacity_factor * T * self.gate.topk / self.num_expert), 4)
        out = self._forward_eager(x2, gate_idx, gate_val, C)
        return out.reshape(orig_shape)

    def _forward_eager(self, x2, gate_idx, gate_val, C):
        from .dispatch import capacity_slots, combine as combine_fn, dispatch
        E = self.num_expert
        # routing is integer-valued (non-differentiable): compute slot/keep once
        # and close over them in both tape ops
        idx = gate_idx._data if isinstance(gate_idx, Tensor) else jnp.asarray(gate_idx)
        slot, keep = capacity_slots(idx.astype(jnp.int32), E, C)

        expert_in = apply("moe_scatter", lambda xx: dispatch(xx, slot, E, C), x2)
        outs = []
        for e, expert in enumerate(self.experts):
            outs.append(expert(expert_in[e]))
        from .....ops.manipulation import stack
        expert_out = stack(outs, axis=0)
        return apply("moe_gather",
                     lambda val, eo: combine_fn(eo, slot, keep,
                                                val.astype(jnp.float32)),
                     gate_val, expert_out)
