"""Functional MoE token dispatch — the TPU-native `global_scatter`/`global_gather`.

Reference capability: `incubate/distributed/models/moe/moe_layer.py` routes tokens
to experts with capacity-slotted buffers exchanged via the `global_scatter` /
`global_gather` all-to-all ops (`fluid/operators/collective/global_scatter_op.cc`).

TPU-first design here:
- Routing is a *permutation scatter*: each (token, k) assignment gets a unique
  capacity slot `expert_id * C + position_in_queue` computed with one cumsum over a
  `[T*k, E]` one-hot (E is small).  No `[T, k, E, C]` combine tensor is ever
  materialized (the round-1 implementation's memory cliff).
- Slots past capacity map out-of-bounds and XLA's scatter OOB-drop semantics
  discard them — the GShard "token dropping" behavior with zero branching.
- Expert buffers are static-shaped `[E, C, D]`, so the surrounding program stays
  jit-friendly, and under an `ep` mesh axis the buffers are exchanged with
  `jax.lax.all_to_all` inside `shard_map` (see `parallel/hybrid.py:_moe_ffn_ep`)
  — exactly the reference's global_scatter/global_gather, but riding ICI.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def moe_capacity(num_tokens: int, topk: int, num_experts: int,
                 capacity_factor: float) -> int:
    """Static per-expert queue length (ref MoELayer capacity computation)."""
    return max(int(math.ceil(capacity_factor * num_tokens * topk / num_experts)), 4)


def topk_gating(logits, topk: int, normalize: bool = True):
    """Softmax-top-k router (GShard top-2 / Switch top-1 family).

    Returns (gate_idx [T,k] int32, gate_val [T,k] f32, aux_loss scalar).
    aux is the Switch load-balance loss: E * sum_e(frac_tokens_e * mean_prob_e).
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]
    gate_val, gate_idx = jax.lax.top_k(probs, topk)
    if normalize and topk > 1:
        gate_val = gate_val / jnp.maximum(
            jnp.sum(gate_val, axis=-1, keepdims=True), 1e-9)
    E = logits.shape[-1]
    me = jnp.mean(probs, axis=0)                                  # mean prob per e
    ce = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], E, dtype=jnp.float32), axis=0)
    aux = E * jnp.sum(me * ce)
    return gate_idx.astype(jnp.int32), gate_val, aux


def capacity_slots(gate_idx, num_experts: int, capacity: int):
    """Assign each (token, k) routing a unique slot in its expert's queue.

    Returns (slot [T,k] int32 in [0, E*C] — E*C means dropped, keep [T,k] bool).
    """
    T, k = gate_idx.shape
    E, C = num_experts, capacity
    onehot = jax.nn.one_hot(gate_idx.reshape(T * k), E, dtype=jnp.int32)
    # position of each assignment within its expert's queue (arrival order)
    pos = (jnp.cumsum(onehot, axis=0) * onehot).sum(-1).reshape(T, k) - 1
    keep = pos < C
    slot = jnp.where(keep, gate_idx * C + pos, E * C)  # OOB slot == dropped
    return slot, keep


def dispatch(x, slot, num_experts: int, capacity: int):
    """x [T, D] -> expert buffers [E, C, D].  Slots are unique, so this is a
    permutation scatter; OOB (dropped) slots vanish per XLA scatter semantics."""
    T, D = x.shape
    k = slot.shape[1]
    EC = num_experts * capacity
    buf = jnp.zeros((EC, D), x.dtype)
    xk = jnp.broadcast_to(x[:, None, :], (T, k, D)).reshape(T * k, D)
    buf = buf.at[slot.reshape(T * k)].set(xk, mode="drop")
    return buf.reshape(num_experts, capacity, D)


def combine(expert_out, slot, keep, gate_val):
    """expert buffers [E, C, D] -> [T, D], weighting by gate values; dropped
    assignments contribute zero (the GShard residual-passthrough convention is
    applied by the caller via the residual add)."""
    E, C, D = expert_out.shape
    T, k = slot.shape
    flat = expert_out.reshape(E * C, D)
    picked = flat[jnp.clip(slot, 0, E * C - 1).reshape(T * k)].reshape(T, k, D)
    w = (gate_val * keep.astype(gate_val.dtype)).astype(picked.dtype)
    return jnp.einsum("tk,tkd->td", w, picked)


def expert_ffn(buf, fc1_w, fc1_b, fc2_w, fc2_b, activation: str = "gelu"):
    """Batched per-expert MLP: buf [E, C, D] x fc1_w [E, D, F] -> [E, C, D]."""
    h = jnp.einsum("ecd,edf->ecf", buf, fc1_w) + fc1_b[:, None, :]
    h = jax.nn.gelu(h) if activation == "gelu" else jax.nn.silu(h)
    return jnp.einsum("ecf,efd->ecd", h, fc2_w) + fc2_b[:, None, :]


def moe_ffn_dense(bp, x, config):
    """Single-group MoE FFN (no ep axis): x [T, D] -> ([T, D], aux).

    bp holds this block's expert weights: gate_w [D, E], exp_fc1_w [E, D, F],
    exp_fc1_b [E, F], exp_fc2_w [E, F, D], exp_fc2_b [E, D].
    """
    E = config.moe_num_experts
    k = config.moe_topk
    T = x.shape[0]
    C = moe_capacity(T, k, E, config.moe_capacity_factor)
    logits = jnp.matmul(x, bp["gate_w"])
    gate_idx, gate_val, aux = topk_gating(logits, k)
    slot, keep = capacity_slots(gate_idx, E, C)
    buf = dispatch(x, slot, E, C)
    out = expert_ffn(buf, bp["exp_fc1_w"], bp["exp_fc1_b"],
                     bp["exp_fc2_w"], bp["exp_fc2_b"], config.activation)
    return combine(out, slot, keep, gate_val), aux
