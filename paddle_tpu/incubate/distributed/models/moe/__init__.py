from .gate import GShardGate, NaiveGate, SwitchGate  # noqa
from .moe_layer import MoELayer  # noqa
from .grad_clip import ClipGradForMOEByGlobalNorm  # noqa
