"""MoE-aware global-norm clip (reference: moe/grad_clip.py — expert params' norms
reduced over the moe group, shared params counted once)."""
from __future__ import annotations

import jax.numpy as jnp

from .....core.tensor import Tensor
from .....nn.clip import ClipGradByGlobalNorm


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None,
                 group_name="default_moe_group"):
        super().__init__(clip_norm, group_name)
        self.is_expert_fn = is_expert_param_func or (
            lambda p: getattr(p, "is_expert", False))
        self.moe_group = moe_group

    def _dygraph_clip(self, params_grads):
        normal_sq = []
        expert_sq = []
        for p, g in params_grads:
            if g is None:
                continue
            sq = jnp.sum(jnp.square(g._data.astype(jnp.float32)))
            (expert_sq if self.is_expert_fn(p) else normal_sq).append(sq)
        total_sq = sum(normal_sq) + sum(expert_sq) if (normal_sq or expert_sq) else None
        if total_sq is None:
            return params_grads
        if self.moe_group is not None and self.moe_group.nranks > 1:
            from .....distributed.communication.ops import ReduceOp, all_reduce
            e = Tensor(jnp.asarray(sum(expert_sq) if expert_sq else 0.0))
            all_reduce(e, ReduceOp.SUM, group=self.moe_group)
            total_sq = sum(normal_sq) + e._data
        global_norm = jnp.sqrt(total_sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [(p, g if g is None else Tensor((g._data * scale).astype(g._data.dtype),
                                               stop_gradient=True))
                for p, g in params_grads]
