"""MoE gates (reference: `incubate/distributed/models/moe/gate/` — naive, gshard,
switch)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor, apply
from .....nn import functional as F
from .....nn.initializer import XavierNormal
from .....nn.layer.layers import Layer


class BaseGate(Layer):
    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert
        self.world_size = world_size
        self.tot_expert = num_expert * world_size
        self.topk = topk
        self.loss = None

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss


class NaiveGate(BaseGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(d_model, num_expert, world_size, topk)
        self.gate_weight = self.create_parameter(
            [d_model, self.tot_expert], default_initializer=XavierNormal())

    def forward(self, inp):
        logits = inp.matmul(self.gate_weight)
        from .....ops.search import topk as _topk
        vals, idx = _topk(logits, self.topk, axis=-1)
        return idx, F.softmax(vals, axis=-1)


class GShardGate(NaiveGate):
    """Top-2 gate with load-balancing aux loss (reference gshard_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity = capacity

    def forward(self, inp):
        logits = inp.matmul(self.gate_weight)
        probs = F.softmax(logits, axis=-1)
        from .....ops.search import topk as _topk
        vals, idx = _topk(probs, self.topk, axis=-1)
        # aux loss: mean_prob_per_expert * frac_tokens_per_expert * E
        E = self.tot_expert

        def aux(p, top1):
            me = jnp.mean(p, axis=0)
            ce = jnp.mean(jax.nn.one_hot(top1.astype(jnp.int32), E), axis=0)
            return jnp.sum(me * ce) * E
        self.loss = apply("gshard_aux_loss", aux, probs, idx[:, 0])
        denom = vals.sum(axis=-1, keepdim=True)
        return idx, vals / denom


class SwitchGate(BaseGate):
    """Top-1 switch gate (reference switch_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1, switch_eps=0.1,
                 capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, 1)
        self.switch_eps = switch_eps
        self.gate_weight = self.create_parameter(
            [d_model, self.tot_expert], default_initializer=XavierNormal())

    def forward(self, inp):
        logits = inp.matmul(self.gate_weight)
        if self.training and self.switch_eps > 0:
            from .....ops.random import uniform
            noise = uniform(logits.shape, min=1.0 - self.switch_eps,
                            max=1.0 + self.switch_eps)
            logits = logits * noise
        probs = F.softmax(logits, axis=-1)
        from .....ops.search import topk as _topk
        vals, idx = _topk(probs, 1, axis=-1)
        E = self.tot_expert

        def aux(p, top1):
            me = jnp.mean(p, axis=0)
            ce = jnp.mean(jax.nn.one_hot(top1.astype(jnp.int32), E), axis=0)
            return jnp.sum(me * ce) * E
        self.loss = apply("switch_aux_loss", aux, probs, idx[:, 0])
        return idx, vals
