from . import moe  # noqa
