from . import models  # noqa
