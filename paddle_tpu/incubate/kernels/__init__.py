"""Pallas TPU kernels for the fused-op inventory (reference:
`paddle/phi/kernels/fusion/gpu/` CUDA kernels -> Mosaic/Pallas here)."""
