"""Paged attention decode for TPU serving (ref vLLM PagedAttention, Kwon et al.
SOSP 2023; reference repo counterpart: the fused variable-length attention used
by `fluid/inference` / PaddleNLP generation predictors).

The serving engine stores KV in a static pool of fixed-size pages
(`[num_pages, page_size, KVH, hd]` per layer) plus a per-slot page table, so
cache memory scales with live tokens instead of `B * max_seq_len`.  Decode
attention then has to read each slot's keys/values *through* the page table:

- `paged_attention_xla`: gather-based implementation (`pool[page_table]`) — the
  CPU/debug fallback and the numerics oracle for tests.  XLA lowers the gather
  to a dynamic-slice loop; fine at test scale, bandwidth-wasteful at pool scale
  because the gathered `[B, S_max, KVH, hd]` copy round-trips HBM.
- `paged_attention_pallas`: Pallas TPU kernel using `PrefetchScalarGridSpec` —
  the page table and per-slot lengths are scalar-prefetched so the BlockSpec
  index_map DMAs each slot's pages HBM->VMEM directly (no materialized gather),
  with online-softmax accumulation over the page grid dimension and per-page
  length masking.  Pages past a slot's length (including the reserved null
  page 0) are masked out; whole pages beyond the length skip compute.

Layout note: one query token per slot (`q [B, H, hd]`) — decode T=1 is the hot
case the engine compiles once.  GQA folds into the kernel as G = H // KVH query
rows per kv head.

Chunked prefill (Sarathi-Serve, Agrawal et al. OSDI 2024) adds the
`*_prefill_*` pair: a chunk of T query tokens starting at position
`q_offset != 0` attends through the same page table with the causal mask
`kv_pos <= q_offset + t` — positions below the offset are the already-written
prefix (cached pages or earlier chunks), positions inside the chunk mask
causally.  The `q_offset` lane rides the scalar prefetch next to the page
table in the Pallas kernel and is a broadcast add in the XLA oracle.

Speculative decode (Leviathan et al. 2023) verifies `spec_len + 1` candidate
tokens per slot in one pass.  That IS the q_len > 1 decode case: query t sits
at position `lengths[b] + t` and attends causally through the page table —
exactly the prefill pair's contract with `q_offset = lengths` and per-slot
`valid` counts (`valid = 1` degenerates to vanilla single-token decode, which
is how undrafted slots ride the same fixed-shape verify executable).
`paged_verify_attention` is that entry.

The fused one-dispatch serving step (`models.gpt.serve_step_paged`) takes the
q_offset/valid contract to its conclusion: `paged_serve_attention` is the
single attention entry behind the engine's steady-state step, where EVERY
slot — vanilla decode (valid = 1), spec verify (valid = 1+K) and the
interleaved prefill chunk (valid = chunk tokens) — rides one kernel grid with
its own per-slot q_offset/valid mask.  The per-slot mode is entirely encoded
by those masks plus the page-table row (inactive slots are null rows), so the
decode-side program budget collapses to ONE compiled executable.

Multi-chip serving (PR 4) makes every entry mesh-aware: pass `mesh=` with an
'mp' axis and the attention runs head-sharded tensor-parallel — the
`paged_*_mp` wrappers shard q on its head axis and the pool on KVH, running
the unmodified Pallas kernel per-shard (shard_map) or the XLA oracle under
sharding constraints.  See the block comment above `_POOL_SPEC`.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .flash_attention import NEG_INF, _on_tpu

# Tensor-parallel serving (multi-chip): attention is embarrassingly parallel
# over heads — no cross-head reduction anywhere in the softmax/PV chain — so
# the mp distribution is "each chip owns H/mp query heads and KVH/mp kv heads
# of EVERY page".  The page pool shards on its KVH axis, q on its head axis,
# and the page table / lengths / q_offset / valid scalars stay replicated
# (they are host-side scheduler state, identical on every chip).  Two routes:
# - Pallas (TPU): the kernel is grid-per-shard — shard_map_compat (the PR-1
#   full-manual fallback on old JAX) runs the UNMODIFIED kernel on the local
#   head slice of the pool.
# - XLA oracle (CPU / kernel-unfriendly layouts): sharding constraints pin the
#   head layout and GSPMD partitions the gather+einsum (the gather indexes the
#   pool's page axis, which is unsharded, so it stays collective-free).
_POOL_SPEC = P(None, None, "mp", None)      # [num_pages, page, KVH, hd]
# quantized-pool scale lanes [num_pages, page, KVH]: per-token-per-head f32
# scales shard on the SAME KVH axis as the int8 pages they dequantize
_SCALE_SPEC = P(None, None, "mp")


def _mp_degree(mesh) -> int:
    if mesh is None:
        return 1
    return int(dict(mesh.shape).get("mp", 1))


def _check_mp_heads(q_heads: int, kv_heads: int, mp: int) -> None:
    if q_heads % mp or kv_heads % mp:
        raise ValueError(
            f"tensor-parallel serving needs num_heads ({q_heads}) and "
            f"kv_heads ({kv_heads}) divisible by mp={mp}")


def _head_spec(ndim: int) -> P:
    """Shard the second-to-last ([..., H, hd]) axis over mp."""
    return P(*([None] * (ndim - 2)), "mp", None)


def _pin(mesh, x, spec):
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def paged_attention_decode_mp(q, k_pages, v_pages, page_table, lengths,
                              mesh, scale=None, use_pallas=None,
                              interpret=False, kv_scales=None):
    """Head-sharded `paged_attention_decode` over the `mp` axis of `mesh`.

    use_pallas=None auto-selects (TPU + kernel-friendly layout); tests force
    True with interpret=True to run the shard_mapped kernel on CPU.
    kv_scales (int8 pool) shard on the same KVH axis as the pages — the
    dequant is per-head-local, so the mp distribution is unchanged."""
    from ...parallel.ring_attention import shard_map_compat

    mp = _mp_degree(mesh)
    _check_mp_heads(q.shape[1], k_pages.shape[2], mp)
    if use_pallas is None:
        use_pallas = _on_tpu() and _shapes_ok_for_pallas(
            q, k_pages, quantized=kv_scales is not None)
    if use_pallas:
        if kv_scales is not None:
            def local_q(tbl, ln, q_l, k_l, v_l, ks_l, vs_l):
                return paged_attention_pallas(q_l, k_l, v_l, tbl, ln,
                                              scale=scale, interpret=interpret,
                                              kv_scales=(ks_l, vs_l))
            return shard_map_compat(
                local_q, mesh=mesh, axis_names={"mp"},
                in_specs=(P(None, None), P(None), _head_spec(3), _POOL_SPEC,
                          _POOL_SPEC, _SCALE_SPEC, _SCALE_SPEC),
                out_specs=_head_spec(3))(page_table, lengths, q, k_pages,
                                         v_pages, *kv_scales)

        def local(tbl, ln, q_l, k_l, v_l):
            return paged_attention_pallas(q_l, k_l, v_l, tbl, ln, scale=scale,
                                          interpret=interpret)
        return shard_map_compat(
            local, mesh=mesh, axis_names={"mp"},
            in_specs=(P(None, None), P(None), _head_spec(3), _POOL_SPEC,
                      _POOL_SPEC),
            out_specs=_head_spec(3))(page_table, lengths, q, k_pages, v_pages)
    q = _pin(mesh, q, _head_spec(3))
    k_pages = _pin(mesh, k_pages, _POOL_SPEC)
    v_pages = _pin(mesh, v_pages, _POOL_SPEC)
    if kv_scales is not None:
        kv_scales = (_pin(mesh, kv_scales[0], _SCALE_SPEC),
                     _pin(mesh, kv_scales[1], _SCALE_SPEC))
    out = paged_attention_xla(q, k_pages, v_pages, page_table, lengths,
                              scale=scale, kv_scales=kv_scales)
    return _pin(mesh, out, _head_spec(3))


def paged_prefill_attention_mp(q, k_pages, v_pages, page_table, q_offset,
                               valid, mesh, scale=None, use_pallas=None,
                               interpret=False, kv_scales=None):
    """Head-sharded `paged_prefill_attention` (and, via
    `paged_verify_attention`, the spec-decode verify lane) over `mp`."""
    from ...parallel.ring_attention import shard_map_compat

    mp = _mp_degree(mesh)
    _check_mp_heads(q.shape[2], k_pages.shape[2], mp)
    if use_pallas is None:
        use_pallas = _on_tpu() and _shapes_ok_for_pallas(
            q, k_pages, quantized=kv_scales is not None)
    if use_pallas:
        if kv_scales is not None:
            def local_q(tbl, qo, vl, q_l, k_l, v_l, ks_l, vs_l):
                return paged_prefill_attention_pallas(
                    q_l, k_l, v_l, tbl, qo, vl, scale=scale,
                    interpret=interpret, kv_scales=(ks_l, vs_l))
            return shard_map_compat(
                local_q, mesh=mesh, axis_names={"mp"},
                in_specs=(P(None, None), P(None), P(None), _head_spec(4),
                          _POOL_SPEC, _POOL_SPEC, _SCALE_SPEC, _SCALE_SPEC),
                out_specs=_head_spec(4))(page_table, q_offset, valid, q,
                                         k_pages, v_pages, *kv_scales)

        def local(tbl, qo, vl, q_l, k_l, v_l):
            return paged_prefill_attention_pallas(q_l, k_l, v_l, tbl, qo, vl,
                                                  scale=scale,
                                                  interpret=interpret)
        return shard_map_compat(
            local, mesh=mesh, axis_names={"mp"},
            in_specs=(P(None, None), P(None), P(None), _head_spec(4),
                      _POOL_SPEC, _POOL_SPEC),
            out_specs=_head_spec(4))(page_table, q_offset, valid, q, k_pages,
                                     v_pages)
    q = _pin(mesh, q, _head_spec(4))
    k_pages = _pin(mesh, k_pages, _POOL_SPEC)
    v_pages = _pin(mesh, v_pages, _POOL_SPEC)
    if kv_scales is not None:
        kv_scales = (_pin(mesh, kv_scales[0], _SCALE_SPEC),
                     _pin(mesh, kv_scales[1], _SCALE_SPEC))
    out = paged_prefill_attention_xla(q, k_pages, v_pages, page_table,
                                      q_offset, valid, scale=scale,
                                      kv_scales=kv_scales)
    return _pin(mesh, out, _head_spec(4))


def _dequant_gathered(pages, scales, page_table, B, S, KVH, hd):
    """Gather int8 pages through the table and dequantize by their per-token
    scales (float32) — the oracle twin of the kernels' per-page dequant."""
    x = pages[page_table].reshape(B, S, KVH, hd).astype(jnp.float32)
    s = scales[page_table].reshape(B, S, KVH)
    return x * s[..., None]


def paged_attention_xla(q, k_pages, v_pages, page_table, lengths, scale=None,
                        kv_scales=None):
    """Gather-based paged decode attention (fallback + oracle).

    q: [B, H, hd] — one query token per slot.
    k_pages/v_pages: [P, page_size, KVH, hd] — the page pool for one layer.
    page_table: [B, max_pages] int32 page ids (0 = reserved null page).
    lengths: [B] int32 — number of valid tokens per slot (including the token
        just written at position lengths-1).
    kv_scales: (k_scale, v_scale) [P, page_size, KVH] float32 for an int8
        pool — gathered pages dequantize to float32 before the score/PV
        matmuls (same math as the Pallas kernels, so parity stays exact).
    Returns [B, H, hd].
    """
    B, H, hd = q.shape
    page = k_pages.shape[1]
    KVH = k_pages.shape[2]
    G = H // KVH
    S = page_table.shape[1] * page
    s = scale if scale is not None else 1.0 / math.sqrt(hd)
    if kv_scales is not None:
        k = _dequant_gathered(k_pages, kv_scales[0], page_table, B, S, KVH, hd)
        v = _dequant_gathered(v_pages, kv_scales[1], page_table, B, S, KVH, hd)
    else:
        k = k_pages[page_table].reshape(B, S, KVH, hd)
        v = v_pages[page_table].reshape(B, S, KVH, hd)
    qg = q.reshape(B, KVH, G, hd)
    logits = jnp.einsum("bkgd,bskd->bkgs", qg, k,
                        preferred_element_type=jnp.float32) * s
    kv_pos = jnp.arange(S)
    logits = jnp.where(kv_pos[None, None, None] < lengths[:, None, None, None],
                       logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", p.astype(v.dtype), v)
    return out.reshape(B, H, hd)


def _paged_attn_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, *refs,
                       page: int, KVH: int, G: int,
                       n_pages: int, scale: float, quantized: bool = False):
    """Grid (B, max_pages): slots parallel, pages innermost with online-softmax
    scratch carry (acc, m, l) — same discipline as the flash forward kernel,
    but the k/v blocks arrive via the scalar-prefetched page table.  With
    `quantized`, two extra scale refs ([1, page, KVH] float32) follow v_ref
    and the int8 page block dequantizes to f32 right after its DMA — the
    per-page dequant-on-read that keeps the fp pool out of HBM entirely."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        o_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    j = pl.program_id(1)
    H = KVH * G

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    k_start = j * page

    # whole page past the slot's length (null-page tail entries): skip compute
    @pl.when(k_start < length)
    def _compute():
        q = q_ref[0]                                    # [H, hd]
        k = k_ref[0]                                    # [page, KVH, hd]
        v = v_ref[0]
        if quantized:
            k = k.astype(jnp.float32) * ks_ref[0][..., None]
            v = v.astype(jnp.float32) * vs_ref[0][..., None]
        # GQA: per-kv-head score tiles stacked back to [H, page] rows
        rows = []
        for kh in range(KVH):
            qh = q[kh * G:(kh + 1) * G]                 # [G, hd]
            rows.append(jnp.dot(qh, k[:, kh, :].T,
                                preferred_element_type=jnp.float32))
        s = (jnp.concatenate(rows, axis=0) if KVH > 1 else rows[0]) * scale
        pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)
        s = jnp.where(pos < length, s, NEG_INF)         # [H, page]
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                          # [H, page]
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        upd = []
        for kh in range(KVH):
            ph = p[kh * G:(kh + 1) * G].astype(v.dtype)
            upd.append(jnp.dot(ph, v[:, kh, :],
                               preferred_element_type=jnp.float32))
        pv = jnp.concatenate(upd, axis=0) if KVH > 1 else upd[0]   # [H, hd]
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = m_new

    @pl.when(j == n_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_attention_pallas(q, k_pages, v_pages, page_table, lengths,
                           scale=None, interpret=False, kv_scales=None):
    """Pallas paged decode attention — same contract as `paged_attention_xla`.

    The page table and lengths ride `PrefetchScalarGridSpec` so the k/v
    BlockSpec index_maps resolve `pool[table[b, j]]` at DMA time; the pool is
    never gathered into a dense per-slot copy.  With `kv_scales` (int8 pool)
    the per-page scale blocks ride the SAME table-indexed DMA and the page
    dequantizes in VMEM on read.  `interpret=True` runs the kernel on CPU
    for numerics tests.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, hd = q.shape
    page = k_pages.shape[1]
    KVH = k_pages.shape[2]
    G = H // KVH
    n_pages = page_table.shape[1]
    s = scale if scale is not None else 1.0 / math.sqrt(hd)

    kernel = functools.partial(_paged_attn_kernel, page=page, KVH=KVH, G=G,
                               n_pages=n_pages, scale=s,
                               quantized=kv_scales is not None)
    pool_spec = pl.BlockSpec((1, page, KVH, hd),
                             lambda b, j, tbl, ln: (tbl[b, j], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, H, hd), lambda b, j, tbl, ln: (b, 0, 0)),
        pool_spec, pool_spec,
    ]
    args = [q, k_pages, v_pages]
    if kv_scales is not None:
        scale_spec = pl.BlockSpec((1, page, KVH),
                                  lambda b, j, tbl, ln: (tbl[b, j], 0, 0))
        in_specs += [scale_spec, scale_spec]
        args += [kv_scales[0], kv_scales[1]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # (page_table, lengths)
        grid=(B, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, hd), lambda b, j, tbl, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, hd), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    cparams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, hd), q.dtype),
        compiler_params=cparams(dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(page_table, jnp.int32), jnp.asarray(lengths, jnp.int32),
      *args)


def paged_prefill_attention_xla(q, k_pages, v_pages, page_table, q_offset,
                                valid, scale=None, kv_scales=None):
    """Gather-based chunked-prefill paged attention (fallback + oracle).

    q: [B, T, H, hd] — a chunk of T query tokens per slot; query t sits at
        absolute position q_offset[b] + t.
    k_pages/v_pages: [P, page_size, KVH, hd] — the page pool for one layer.
    page_table: [B, max_pages] int32 page ids (0 = reserved null page).
    q_offset: [B] int32 — absolute position of q[:, 0] (prefix already
        written below it: cached pages or earlier chunks).
    valid: [B] int32 — real tokens in the chunk; rows t >= valid[b] compute
        garbage the caller ignores (their KV was routed to the null page).
    kv_scales: (k_scale, v_scale) [P, page_size, KVH] float32 for an int8
        pool — per-token dequant on read, same math as the Pallas kernel.
    Returns [B, T, H, hd].
    """
    B, T, H, hd = q.shape
    page = k_pages.shape[1]
    KVH = k_pages.shape[2]
    G = H // KVH
    S = page_table.shape[1] * page
    s = scale if scale is not None else 1.0 / math.sqrt(hd)
    if kv_scales is not None:
        k = _dequant_gathered(k_pages, kv_scales[0], page_table, B, S, KVH, hd)
        v = _dequant_gathered(v_pages, kv_scales[1], page_table, B, S, KVH, hd)
    else:
        k = k_pages[page_table].reshape(B, S, KVH, hd)
        v = v_pages[page_table].reshape(B, S, KVH, hd)
    qg = q.reshape(B, T, KVH, G, hd)
    logits = jnp.einsum("btkgd,bskd->bkgts", qg, k,
                        preferred_element_type=jnp.float32) * s
    qpos = q_offset[:, None] + jnp.arange(T)                    # [B, T]
    mask = jnp.arange(S)[None, None] <= qpos[:, :, None]        # [B, T, S]
    logits = jnp.where(mask[:, None, None], logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgts,bskd->bkgtd", p.astype(v.dtype), v)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T, H, hd)


def _paged_prefill_kernel(tbl_ref, qoff_ref, val_ref, q_ref, k_ref, v_ref,
                          *refs, page: int,
                          KVH: int, G: int, T: int, n_pages: int,
                          scale: float, quantized: bool = False):
    """Grid (B, max_pages): slots parallel, pages innermost with
    online-softmax scratch carry over T*H query rows (kh-major stacking, same
    discipline as the decode kernel).  The causal-at-offset mask
    `kv_pos <= q_offset + t` replaces the decode kernel's length mask; page 0
    always computes (every query row attends at least to kv position 0), so
    the running max is finite before any fully-masked row/page combination.
    `quantized` adds two per-page scale refs after v_ref: the int8 page
    block dequantizes to f32 on read, same math as the decode kernel."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        o_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    j = pl.program_id(1)
    H = KVH * G

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    qoff = qoff_ref[b]
    last_q = qoff + val_ref[b] - 1      # highest real query position
    k_start = j * page

    # page entirely past every real query position: skip compute
    @pl.when(k_start <= last_q)
    def _compute():
        q = q_ref[0]                                    # [T, H, hd]
        k = k_ref[0]                                    # [page, KVH, hd]
        v = v_ref[0]
        if quantized:
            k = k.astype(jnp.float32) * ks_ref[0][..., None]
            v = v.astype(jnp.float32) * vs_ref[0][..., None]
        rows = []
        for kh in range(KVH):
            qh = q[:, kh * G:(kh + 1) * G, :].reshape(T * G, -1)
            rows.append(jnp.dot(qh, k[:, kh, :].T,
                                preferred_element_type=jnp.float32))
        s = (jnp.concatenate(rows, axis=0) if KVH > 1 else rows[0]) * scale
        R = KVH * T * G
        kv_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (R, page), 1)
        t_row = (jax.lax.broadcasted_iota(jnp.int32, (R, page), 0)
                 % (T * G)) // G
        s = jnp.where(kv_pos <= qoff + t_row, s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                          # [R, page]
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        upd = []
        for kh in range(KVH):
            ph = p[kh * T * G:(kh + 1) * T * G].astype(v.dtype)
            upd.append(jnp.dot(ph, v[:, kh, :],
                               preferred_element_type=jnp.float32))
        pv = jnp.concatenate(upd, axis=0) if KVH > 1 else upd[0]   # [R, hd]
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = m_new

    @pl.when(j == n_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        out = acc_ref[...] / l                          # [KVH*T*G, hd]
        for kh in range(KVH):
            blk = out[kh * T * G:(kh + 1) * T * G].reshape(T, G, -1)
            o_ref[0, :, kh * G:(kh + 1) * G, :] = blk.astype(o_ref.dtype)


def paged_prefill_attention_pallas(q, k_pages, v_pages, page_table, q_offset,
                                   valid, scale=None, interpret=False,
                                   kv_scales=None):
    """Pallas chunked-prefill paged attention — same contract as
    `paged_prefill_attention_xla`.  page_table / q_offset / valid ride
    `PrefetchScalarGridSpec`; `kv_scales` (int8 pool) adds table-indexed
    per-page scale blocks dequantized on read; `interpret=True` runs on CPU
    for numerics tests."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, hd = q.shape
    page = k_pages.shape[1]
    KVH = k_pages.shape[2]
    G = H // KVH
    n_pages = page_table.shape[1]
    s = scale if scale is not None else 1.0 / math.sqrt(hd)

    kernel = functools.partial(_paged_prefill_kernel, page=page, KVH=KVH,
                               G=G, T=T, n_pages=n_pages, scale=s,
                               quantized=kv_scales is not None)
    pool_spec = pl.BlockSpec((1, page, KVH, hd),
                             lambda b, j, tbl, qo, vl: (tbl[b, j], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, T, H, hd), lambda b, j, tbl, qo, vl: (b, 0, 0, 0)),
        pool_spec, pool_spec,
    ]
    args = [q, k_pages, v_pages]
    if kv_scales is not None:
        scale_spec = pl.BlockSpec((1, page, KVH),
                                  lambda b, j, tbl, qo, vl: (tbl[b, j], 0, 0))
        in_specs += [scale_spec, scale_spec]
        args += [kv_scales[0], kv_scales[1]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,          # (page_table, q_offset, valid)
        grid=(B, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, T, H, hd),
                               lambda b, j, tbl, qo, vl: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KVH * T * G, hd), jnp.float32),
            pltpu.VMEM((KVH * T * G, 1), jnp.float32),
            pltpu.VMEM((KVH * T * G, 1), jnp.float32),
        ],
    )
    cparams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, T, H, hd), q.dtype),
        compiler_params=cparams(dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(page_table, jnp.int32), jnp.asarray(q_offset, jnp.int32),
      jnp.asarray(valid, jnp.int32), *args)


def paged_prefill_attention(q, k_pages, v_pages, page_table, q_offset, valid,
                            scale=None, mesh=None, kv_scales=None):
    """Entry used by `models.gpt.prefill_chunk_paged`: Pallas on TPU when the
    layout is kernel-friendly, gather fallback otherwise.  mesh (with an 'mp'
    axis > 1) runs head-sharded tensor-parallel.  kv_scales (int8 pool)
    selects the per-page dequant-on-read lane in every route."""
    if _mp_degree(mesh) > 1:
        return paged_prefill_attention_mp(q, k_pages, v_pages, page_table,
                                          q_offset, valid, mesh, scale=scale,
                                          kv_scales=kv_scales)
    if _on_tpu() and _shapes_ok_for_pallas(q, k_pages,
                                           quantized=kv_scales is not None):
        return paged_prefill_attention_pallas(q, k_pages, v_pages, page_table,
                                              q_offset, valid, scale=scale,
                                              kv_scales=kv_scales)
    return paged_prefill_attention_xla(q, k_pages, v_pages, page_table,
                                       q_offset, valid, scale=scale,
                                       kv_scales=kv_scales)


def _shapes_ok_for_pallas(q, k_pages, quantized=False):
    hd = q.shape[-1]
    page = k_pages.shape[1]
    ok = hd in (64, 128, 256) and page % 8 == 0
    if quantized:
        # int8 VMEM tiles are (32, 128) (pallas guide): keep the auto-route
        # to the kernel conservative on quantized pools — hd a full lane
        # width and whole-sublane pages — until the int8 layout is validated
        # on real hardware; anything else takes the XLA dequant-gather path
        ok = ok and hd in (128, 256) and page % 32 == 0
    return ok


def paged_verify_attention(q, k_pages, v_pages, page_table, lengths, valid,
                           scale=None, mesh=None, kv_scales=None):
    """Entry used by `models.gpt.verify_step_paged`: multi-token (q_len > 1)
    decode over the paged pool.  q [B, T, H, hd] holds the last emitted token
    plus up to T-1 drafted tokens per slot; query t sits at absolute position
    `lengths[b] + t`, and rows t >= valid[b] are padding whose output the
    scheduler ignores (their KV was routed to the null page).  Same math as
    the chunked-prefill pair with `q_offset = lengths` — one kernel serves
    both lanes, keeping the decode-side compiled-program count at two."""
    return paged_prefill_attention(q, k_pages, v_pages, page_table, lengths,
                                   valid, scale=scale, mesh=mesh,
                                   kv_scales=kv_scales)


def paged_serve_attention(q, k_pages, v_pages, page_table, q_offset, valid,
                          scale=None, mesh=None, kv_scales=None):
    """Entry used by `models.gpt.serve_step_paged` — the fused one-dispatch
    engine step.  Identical math to the prefill/verify pair (causal-at-offset
    through the page table), but the batch is heterogeneous: each slot's
    (q_offset, valid) pair selects its mode — decode rides at valid=1 with
    q_offset = cached length, verify at valid=1+K, a prefill chunk at
    valid = chunk tokens with q_offset = tokens already written — and padded
    rows (t >= valid) are masked per slot, their KV routed to the null page
    by the caller.  One kernel serves every lane of the steady-state step,
    which is what lets the engine dispatch exactly one program per
    iteration."""
    return paged_prefill_attention(q, k_pages, v_pages, page_table, q_offset,
                                   valid, scale=scale, mesh=mesh,
                                   kv_scales=kv_scales)


def paged_attention_decode(q, k_pages, v_pages, page_table, lengths,
                           scale=None, mesh=None, kv_scales=None):
    """Entry used by `models.gpt.decode_step_paged`: Pallas on TPU when the
    layout is kernel-friendly, gather fallback otherwise.  mesh (with an 'mp'
    axis > 1) runs head-sharded tensor-parallel."""
    if _mp_degree(mesh) > 1:
        return paged_attention_decode_mp(q, k_pages, v_pages, page_table,
                                         lengths, mesh, scale=scale,
                                         kv_scales=kv_scales)
    if _on_tpu() and _shapes_ok_for_pallas(q, k_pages,
                                           quantized=kv_scales is not None):
        return paged_attention_pallas(q, k_pages, v_pages, page_table, lengths,
                                      scale=scale, kv_scales=kv_scales)
    return paged_attention_xla(q, k_pages, v_pages, page_table, lengths,
                               scale=scale, kv_scales=kv_scales)
