"""Rotary position embedding (reference: `phi/kernels/fusion/gpu/fused_rope_kernel.cu`).

Pure jnp: a rope application is elementwise muls/adds that XLA fuses into the
surrounding matmul epilogue on TPU — a dedicated kernel buys nothing here.
"""
from __future__ import annotations

import jax.numpy as jnp


def apply_rope(x, sin, cos, neox_style=True):
    """x: [B, S, H, D]; sin/cos: [S, D/2] (or [B, S, D/2] after position-id gather)."""
    D = x.shape[-1]
    half = D // 2
    if sin.ndim == 2:
        sin_b = sin[None, :, None, :]
        cos_b = cos[None, :, None, :]
    else:
        sin_b = sin[:, :, None, :]
        cos_b = cos[:, :, None, :]
    x32 = x.astype(jnp.float32)
    if neox_style:
        x1 = x32[..., :half]
        x2 = x32[..., half:]
        o1 = x1 * cos_b - x2 * sin_b
        o2 = x2 * cos_b + x1 * sin_b
        out = jnp.concatenate([o1, o2], axis=-1)
    else:
        x1 = x32[..., 0::2]
        x2 = x32[..., 1::2]
        o1 = x1 * cos_b - x2 * sin_b
        o2 = x2 * cos_b + x1 * sin_b
        out = jnp.stack([o1, o2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)
