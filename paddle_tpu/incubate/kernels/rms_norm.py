"""Fused RMSNorm Pallas kernel (reference: `phi/kernels/fusion/gpu/
fused_rms_norm_kernel`).

Row-tiled: each program normalizes a [block_rows, D] tile in VMEM — one HBM read, one
write.  Backward is the standard analytic pullback, expressed in jnp (XLA fuses it).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[:] = (x * jax.lax.rsqrt(var + eps)).astype(o_ref.dtype) * w_ref[:]


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except (RuntimeError, IndexError):   # backend init failed / no devices
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_pallas(x2d, w, eps):
    return _rms_fwd_impl(x2d, w, eps)


def _rms_fwd_impl(x2d, w, eps):
    from jax.experimental import pallas as pl

    N, D = x2d.shape
    block = 256
    while N % block != 0:
        block //= 2
    block = max(block, 1)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(N // block,),
        in_specs=[pl.BlockSpec((block, D), lambda i: (i, 0)),
                  pl.BlockSpec((D,), lambda i: (0,))],
        out_specs=pl.BlockSpec((block, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, D), x2d.dtype),
    )(x2d, w)


def _rms_ref(x, w, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _rms_fwd(x2d, w, eps):
    return _rms_fwd_impl(x2d, w, eps), (x2d, w)


def _rms_bwd(eps, res, g):
    x2d, w = res
    _, vjp = jax.vjp(lambda x_, w_: _rms_ref(x_, w_, eps), x2d, w)
    return vjp(g)


_rms_pallas.defvjp(_rms_fwd, _rms_bwd)


def rms_norm_fused(x, w, eps=1e-6):
    """x: [..., D]; w: [D]."""
    D = x.shape[-1]
    if _on_tpu() and D % 128 == 0 and x.size // D >= 8:
        x2d = x.reshape(-1, D)
        out = _rms_pallas(x2d, w, eps)
        return out.reshape(x.shape)
    return _rms_ref(x, w, eps)
