"""Flash attention for TPU.

Reference parity: `phi/kernels/gpu/flash_attn_kernel.cu` and
`flash_attn_grad_kernel.cu` (wrapping the flashattn CUDA lib).
TPU-native: Pallas kernels with online-softmax tiling —

- forward: K blocks form the innermost ("arbitrary") grid dimension with VMEM
  scratch carrying (acc, m, l); emits the per-row logsumexp `lse` alongside the
  output so the backward never re-runs the full forward.
- backward: two tiled kernels recomputing p = exp(s - lse) blockwise (the standard
  flash-attention-2 dq / dkv split) — no S×S materialization, causal block skip in
  both directions.

Remat interplay: the custom_vjp forward tags its residuals (`flash_out`,
`flash_lse`) with `checkpoint_name`, so a surrounding `jax.checkpoint(policy=
save_only_these_names('flash_out', 'flash_lse'))` saves exactly those and the
block replay skips re-running the attention kernel entirely — q/k/v residuals are
recomputed by the (cheap) qkv-matmul replay while the kernel outputs come from the
saved names.  This kills the round-1 "attention forward runs ~3x" remat tax.

Fallbacks: CPU/debug or masked/dropout paths use the XLA composed implementation;
the Pallas path covers the causal/no-mask hot case used by GPT pretraining.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu",) or \
            jax.devices()[0].platform in ("tpu", "axon")
    except (RuntimeError, IndexError):   # backend init failed / no devices
        return False


# ---------------------------------------------------------------------------
# XLA reference implementation (fallback + numerics oracle for tests)
# ---------------------------------------------------------------------------

def attention_xla(q, k, v, mask=None, causal=False, scale=None, dropout_p=0.0,
                  dropout_key=None):
    """q,k,v: [B, S, H, D] (paddle layout)."""
    D = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * s
    if causal:
        Lq, Lk = q.shape[1], k.shape[1]
        row = jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
        cmask = row + (Lk - Lq) >= col
        logits = jnp.where(cmask[None, None], logits, NEG_INF)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, NEG_INF)
        else:
            logits = logits + mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out


# ---------------------------------------------------------------------------
# Pallas forward kernel: grid (BH, n_q, n_k), K innermost with scratch carry
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                      *, block_q: int, block_k: int, n_k: int, causal: bool,
                      scale: float):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    # causal: whole block above the diagonal contributes nothing — skip compute
    run = True
    if causal:
        run = q_start + block_q - 1 >= k_start

    @pl.when(run if causal else (ki >= 0))
    def _compute():
        # keep MXU operands in the input dtype (bf16 runs 4x f32 on v5e);
        # accumulation stays f32 via preferred_element_type
        q = q_ref[0]                                    # [bq, D]
        k = k_ref[0]                                    # [bk, D]
        v = v_ref[0]                                    # [bk, D]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            row = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            col = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(row >= col, s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)      # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[...] = m_new
        l_ref[...] = l_new

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(l)            # [bq, 1]


# block sizes: bigger q/k tiles amortize Mosaic per-cell overhead; 1024 measured
# ~2x faster than 256 on v5e for the fwd sweep (VMEM: s/p tile is bq*bk*4 bytes)
FWD_BLOCK = 1024
BWD_BLOCK = 1024


def _pick_block(S: int, pref: int) -> int:
    """Largest block <= pref that divides S (falling back through 512/256/128),
    so odd-but-aligned lengths like 1536 stay on the Pallas path with 512 tiles
    instead of silently hitting the XLA fallback."""
    for b in (pref, 1024, 512, 256, 128):
        if b <= pref and S >= b and S % b == 0:
            return b
    return S


def _flash_fwd_impl(q, k, v, causal, scale):
    """[B,S,H,D] -> (out [B,S,H,D], lse [B*H, S, 1] f32)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    Sk = k.shape[1]
    qt = jnp.transpose(q, (0, 2, 1, 3)).reshape(B * H, S, D)
    kt = jnp.transpose(k, (0, 2, 1, 3)).reshape(B * H, Sk, D)
    vt = jnp.transpose(v, (0, 2, 1, 3)).reshape(B * H, Sk, D)

    block_q = _pick_block(S, FWD_BLOCK)
    block_k = _pick_block(Sk, FWD_BLOCK)
    n_k = Sk // block_k
    grid = (B * H, S // block_q, n_k)
    kernel = functools.partial(_flash_fwd_kernel, block_q=block_q, block_k=block_k,
                               n_k=n_k, causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qt, kt, vt)
    return jnp.transpose(out.reshape(B, H, S, D), (0, 2, 1, 3)), lse


# ---------------------------------------------------------------------------
# Pallas backward kernels (flash-attention-2 split: dkv sweep, dq sweep)
# ---------------------------------------------------------------------------

def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *,
                          block_q: int, block_k: int, n_q: int, causal: bool,
                          scale: float):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = q_start + block_q - 1 >= k_start

    @pl.when(run if causal else (qi >= 0))
    def _compute():
        q = q_ref[0]                                    # [bq, D]
        k = k_ref[0]                                    # [bk, D]
        v = v_ref[0]                                    # [bk, D]
        do = do_ref[0]                                  # [bq, D]
        lse = lse_ref[0]                                # [bq, 1]
        dl = dl_ref[0]                                  # [bq, 1] rowsum(dO*O)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            row = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            col = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(row >= col, s, NEG_INF)
        p = jnp.exp(s - lse)                            # [bq, bk] f32
        pt = p.astype(do.dtype).T
        dv_acc[...] += jnp.dot(pt, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)  # [bq, bk]
        ds = (p * (dp - dl) * scale).astype(q.dtype)
        dk_acc[...] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                         dq_ref, dq_acc, *, block_q: int, block_k: int,
                         n_k: int, causal: bool, scale: float):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = q_start + block_q - 1 >= k_start

    @pl.when(run if causal else (ki >= 0))
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        dl = dl_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            row = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            col = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(row >= col, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - dl) * scale).astype(k.dtype)
        dq_acc[...] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_impl(q, k, v, out, lse, g, causal, scale):
    """Tiled dq/dk/dv.  q,k,v,out,g: [B,S,H,D]; lse: [B*H,S,1] f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    Sk = k.shape[1]
    qt = jnp.transpose(q, (0, 2, 1, 3)).reshape(B * H, S, D)
    kt = jnp.transpose(k, (0, 2, 1, 3)).reshape(B * H, Sk, D)
    vt = jnp.transpose(v, (0, 2, 1, 3)).reshape(B * H, Sk, D)
    dot = jnp.transpose(g, (0, 2, 1, 3)).reshape(B * H, S, D)
    # delta_i = rowsum(dO_i * O_i) — the only residual beyond lse (cheap XLA fuse)
    delta = jnp.sum(dot.astype(jnp.float32) *
                    jnp.transpose(out, (0, 2, 1, 3)).reshape(B * H, S, D)
                    .astype(jnp.float32), axis=-1, keepdims=True)  # [BH,S,1]

    block_q = _pick_block(S, BWD_BLOCK)
    block_k = _pick_block(Sk, BWD_BLOCK)
    n_q = S // block_q
    n_k = Sk // block_k

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, block_q=block_q, block_k=block_k, n_q=n_q,
        causal=causal, scale=scale)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),   # q
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),   # k
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),   # v
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),   # dO
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),   # lse
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),   # delta
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qt, kt, vt, dot, lse, delta)

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, block_q=block_q, block_k=block_k, n_k=n_k,
        causal=causal, scale=scale)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),   # q
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),   # k
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),   # v
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),   # dO
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),   # lse
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),   # delta
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qt, kt, vt, dot, lse, delta)

    tr = lambda x, L: jnp.transpose(x.reshape(B, H, L, D), (0, 2, 1, 3))
    return tr(dq, S), tr(dk, Sk), tr(dv, Sk)


# ---------------------------------------------------------------------------
# custom_vjp wiring (+ checkpoint_name so block-level remat saves out/lse)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_core(q, k, v, causal, scale):
    """[B, S, H, D] in/out; Pallas forward AND backward."""
    out, _ = _flash_fwd_impl(q, k, v, causal, scale)
    return out


def _flash_core_fwd(q, k, v, causal, scale):
    out, lse = _flash_fwd_impl(q, k, v, causal, scale)
    # named so jax.checkpoint(policy=save_only_these_names('flash_out',
    # 'flash_lse')) saves exactly these: the replay then recomputes q/k/v via the
    # cheap qkv matmul but never re-runs the attention kernel
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _flash_core_bwd(causal, scale, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_impl(q, k, v, out, lse, g, causal, scale)


_flash_attention_core.defvjp(_flash_core_fwd, _flash_core_bwd)

# the checkpoint policy matching the names above (used by models + trainers).
# 'flash_qkv' additionally saves the post-rope q/k/v at the call site (see
# models/gpt.py block_forward), letting the block replay DCE the qkv matmul +
# rope forward — they are only needed to produce values that are now saved.
remat_policy_save_attention = functools.partial(
    jax.checkpoint_policies.save_only_these_names,
    "flash_out", "flash_lse", "flash_qkv")


def _shapes_ok_for_pallas(q, k):
    B, S, H, D = q.shape
    Sk = k.shape[1]
    if D not in (64, 128, 256):
        return False
    if S < 128 or Sk < 128:
        return False
    # every length must land on an aligned divisor block
    return all(L % _pick_block(L, pref) == 0 and _pick_block(L, pref) % 128 == 0
               for L in (S, Sk) for pref in (FWD_BLOCK, BWD_BLOCK))


def flash_attention_fused(q, k, v, mask=None, causal=False, scale=None,
                          dropout_p=0.0):
    """Entry used by incubate fused ops.  q,k,v: [B, S, H, D]."""
    D = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    if (mask is None and dropout_p == 0.0 and _on_tpu()
            and _shapes_ok_for_pallas(q, k)):
        return _flash_attention_core(q, k, v, causal, s)
    key = None
    if dropout_p > 0.0:
        from ...core import generator as _gen
        key = _gen.next_key()
    return attention_xla(q, k, v, mask=mask, causal=causal, scale=s,
                         dropout_p=dropout_p, dropout_key=key)


# ---------------------------------------------------------------------------
# Varlen (segment-ids) Pallas kernels — ref flash_attn varlen/unpadded
# (`nn/functional/flash_attention.py:200`): packed sequences attend only within
# their own segment.  Separate kernels so the dense hot path stays untouched.
# ---------------------------------------------------------------------------

def _seg_mask(sq, sk, s, q_start, k_start, block_q, block_k, causal):
    """Combine segment equality (and causality) into the score mask."""
    m = sq[:, 0][:, None] == sk[:, 0][None, :]
    if causal:
        row = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        col = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        m = m & (row >= col)
    return jnp.where(m, s, NEG_INF), m


def _flash_fwd_seg_kernel(q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref, lse_ref,
                          acc_ref, m_ref, l_ref, *, block_q, block_k, n_k,
                          causal, scale):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = q_start + block_q - 1 >= k_start

    @pl.when(run if causal else (ki >= 0))
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s, mask = _seg_mask(sq_ref[0], sk_ref[0], s, q_start, k_start,
                            block_q, block_k, causal)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)  # fully-masked rows: no exp(NEG-NEG) mass
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[...] = m_new
        l_ref[...] = l_new

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(l)


def _flash_bwd_seg_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                              sq_ref, sk_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                              *, block_q, block_k, n_q, causal, scale):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = q_start + block_q - 1 >= k_start

    @pl.when(run if causal else (qi >= 0))
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        dl = dl_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s, mask = _seg_mask(sq_ref[0], sk_ref[0], s, q_start, k_start,
                            block_q, block_k, causal)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        pt = p.astype(do.dtype).T
        dv_acc[...] += jnp.dot(pt, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - dl) * scale).astype(q.dtype)
        dk_acc[...] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_seg_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                             sq_ref, sk_ref, dq_ref, dq_acc, *, block_q,
                             block_k, n_k, causal, scale):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = q_start + block_q - 1 >= k_start

    @pl.when(run if causal else (ki >= 0))
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        dl = dl_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s, mask = _seg_mask(sq_ref[0], sk_ref[0], s, q_start, k_start,
                            block_q, block_k, causal)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - dl) * scale).astype(k.dtype)
        dq_acc[...] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _seg3(seg, B, H, S):
    """[B, S] int32 -> [B*H, S, 1] (per-head broadcast for block indexing)."""
    s = jnp.broadcast_to(seg.astype(jnp.int32)[:, None, :], (B, H, S))
    return s.reshape(B * H, S, 1)


def _flash_seg_fwd_impl(q, k, v, seg_q, seg_k, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    Sk = k.shape[1]
    qt = jnp.transpose(q, (0, 2, 1, 3)).reshape(B * H, S, D)
    kt = jnp.transpose(k, (0, 2, 1, 3)).reshape(B * H, Sk, D)
    vt = jnp.transpose(v, (0, 2, 1, 3)).reshape(B * H, Sk, D)
    sq = _seg3(seg_q, B, H, S)
    sk = _seg3(seg_k, B, H, Sk)

    block_q = _pick_block(S, FWD_BLOCK)
    block_k = _pick_block(Sk, FWD_BLOCK)
    n_k = Sk // block_k
    kernel = functools.partial(_flash_fwd_seg_kernel, block_q=block_q,
                               block_k=block_k, n_k=n_k, causal=causal,
                               scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, S // block_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qt, kt, vt, sq, sk)
    return jnp.transpose(out.reshape(B, H, S, D), (0, 2, 1, 3)), lse


def _flash_seg_bwd_impl(q, k, v, seg_q, seg_k, out, lse, g, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    Sk = k.shape[1]
    qt = jnp.transpose(q, (0, 2, 1, 3)).reshape(B * H, S, D)
    kt = jnp.transpose(k, (0, 2, 1, 3)).reshape(B * H, Sk, D)
    vt = jnp.transpose(v, (0, 2, 1, 3)).reshape(B * H, Sk, D)
    dot = jnp.transpose(g, (0, 2, 1, 3)).reshape(B * H, S, D)
    sq = _seg3(seg_q, B, H, S)
    sk = _seg3(seg_k, B, H, Sk)
    delta = jnp.sum(dot.astype(jnp.float32) *
                    jnp.transpose(out, (0, 2, 1, 3)).reshape(B * H, S, D)
                    .astype(jnp.float32), axis=-1, keepdims=True)

    block_q = _pick_block(S, BWD_BLOCK)
    block_k = _pick_block(Sk, BWD_BLOCK)
    n_q = S // block_q
    n_k = Sk // block_k

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_seg_dkv_kernel, block_q=block_q,
                          block_k=block_k, n_q=n_q, causal=causal, scale=scale),
        grid=(B * H, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, j, i: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qt, kt, vt, dot, lse, delta, sq, sk)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_seg_dq_kernel, block_q=block_q,
                          block_k=block_k, n_k=n_k, causal=causal, scale=scale),
        grid=(B * H, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qt, kt, vt, dot, lse, delta, sq, sk)

    tr = lambda x, L: jnp.transpose(x.reshape(B, H, L, D), (0, 2, 1, 3))
    return tr(dq, S), tr(dk, Sk), tr(dv, Sk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _flash_attention_seg_core(q, k, v, seg_q, seg_k, causal, scale):
    out, _ = _flash_seg_fwd_impl(q, k, v, seg_q, seg_k, causal, scale)
    return out


def _flash_seg_fwd(q, k, v, seg_q, seg_k, causal, scale):
    out, lse = _flash_seg_fwd_impl(q, k, v, seg_q, seg_k, causal, scale)
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, seg_q, seg_k, out, lse)


def _flash_seg_bwd(causal, scale, res, g):
    q, k, v, seg_q, seg_k, out, lse = res
    dq, dk, dv = _flash_seg_bwd_impl(q, k, v, seg_q, seg_k, out, lse, g,
                                     causal, scale)
    return dq, dk, dv, None, None  # integer segment ids carry no tangent


_flash_attention_seg_core.defvjp(_flash_seg_fwd, _flash_seg_bwd)


def attention_xla_segmented(q, k, v, seg_q, seg_k, causal, scale):
    """XLA oracle for the varlen kernel (tests + CPU fallback)."""
    mask = seg_q[:, None, :, None] == seg_k[:, None, None, :]   # [B,1,S,Sk]
    return attention_xla(q, k, v, mask=mask, causal=causal, scale=scale)


def flash_attention_varlen(q, k, v, segment_ids, kv_segment_ids=None,
                           causal=True, scale=None):
    """Segment-masked flash attention (varlen packing): q, k, v [B, S, H, D],
    segment_ids [B, S] int — tokens attend only within their own segment."""
    D = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    seg_k = segment_ids if kv_segment_ids is None else kv_segment_ids
    if _on_tpu() and _shapes_ok_for_pallas(q, k):
        return _flash_attention_seg_core(q, k, v, segment_ids, seg_k,
                                         causal, s)
    return attention_xla_segmented(q, k, v, segment_ids, seg_k, causal, s)
