"""Flash attention for TPU.

Reference parity: `phi/kernels/gpu/flash_attn_kernel.cu` (wraps the flashattn CUDA lib).
TPU-native: a Pallas kernel with online-softmax tiling — K blocks form the innermost
("arbitrary") grid dimension with VMEM scratch carrying (acc, m, l) across iterations,
so there are no in-kernel dynamic slices (Mosaic-friendly for head_dim 64/128/256).
Forward runs the Pallas kernel on TPU; backward uses a rematerializing XLA pullback
(custom_vjp) that XLA fuses into two matmul chains — the standard TPU trade (recompute
beats spilling the S×S matrix to HBM).

Fallbacks: CPU/debug or masked/dropout paths use the XLA composed implementation; the
Pallas path covers the causal/no-mask hot case used by GPT pretraining.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu",) or \
            jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


# ---------------------------------------------------------------------------
# XLA reference implementation (also the VJP recompute path)
# ---------------------------------------------------------------------------

def attention_xla(q, k, v, mask=None, causal=False, scale=None, dropout_p=0.0,
                  dropout_key=None):
    """q,k,v: [B, S, H, D] (paddle layout)."""
    D = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * s
    if causal:
        Lq, Lk = q.shape[1], k.shape[1]
        row = jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
        cmask = row + (Lk - Lq) >= col
        logits = jnp.where(cmask[None, None], logits, NEG_INF)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, NEG_INF)
        else:
            logits = logits + mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out


# ---------------------------------------------------------------------------
# Pallas forward kernel: grid (BH, n_q, n_k), K innermost with scratch carry
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                      block_q: int, block_k: int, n_k: int, causal: bool,
                      scale: float):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    # causal: whole block above the diagonal contributes nothing — skip compute
    run = True
    if causal:
        run = q_start + block_q - 1 >= k_start

    @pl.when(run if causal else (ki >= 0))
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale        # [bq, D]
        k = k_ref[0].astype(jnp.float32)                # [bk, D]
        v = v_ref[0].astype(jnp.float32)                # [bk, D]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            row = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            col = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(row >= col, s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)      # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[...] = m_new
        l_ref[...] = l_new

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def _flash_fwd_impl(q, k, v, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    Sk = k.shape[1]
    qt = jnp.transpose(q, (0, 2, 1, 3)).reshape(B * H, S, D)
    kt = jnp.transpose(k, (0, 2, 1, 3)).reshape(B * H, Sk, D)
    vt = jnp.transpose(v, (0, 2, 1, 3)).reshape(B * H, Sk, D)

    block_q = min(256, S)
    block_k = min(256, Sk)
    n_k = Sk // block_k
    grid = (B * H, S // block_q, n_k)
    kernel = functools.partial(_flash_fwd_kernel, block_q=block_q, block_k=block_k,
                               n_k=n_k, causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qt, kt, vt)
    return jnp.transpose(out.reshape(B, H, S, D), (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_core(q, k, v, causal, scale):
    """[B, S, H, D] in/out; Pallas forward, recompute backward."""
    return _flash_fwd_impl(q, k, v, causal, scale)


def _flash_core_fwd(q, k, v, causal, scale):
    out = _flash_fwd_impl(q, k, v, causal, scale)
    return out, (q, k, v)


def _flash_core_bwd(causal, scale, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: attention_xla(q_, k_, v_, None, causal, scale),
                     q, k, v)
    return vjp(g)


_flash_attention_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _shapes_ok_for_pallas(q, k):
    B, S, H, D = q.shape
    Sk = k.shape[1]
    if D not in (64, 128, 256):
        return False
    bq = min(256, S)
    bk = min(256, Sk)
    return S % bq == 0 and Sk % bk == 0 and S >= 128 and Sk >= 128


def flash_attention_fused(q, k, v, mask=None, causal=False, scale=None,
                          dropout_p=0.0):
    """Entry used by incubate fused ops.  q,k,v: [B, S, H, D]."""
    D = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    if (mask is None and dropout_p == 0.0 and _on_tpu()
            and _shapes_ok_for_pallas(q, k)):
        return _flash_attention_core(q, k, v, causal, s)
    key = None
    if dropout_p > 0.0:
        from ...core import generator as _gen
        key = _gen.next_key()
    return attention_xla(q, k, v, mask=mask, causal=causal, scale=s,
                         dropout_p=dropout_p, dropout_key=key)
