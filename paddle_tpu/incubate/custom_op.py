"""Custom operator registration.

Reference parity: `phi/api/ext/op_meta_info.h:943` (PD_BUILD_OP user ops),
`fluid/framework/custom_operator.cc`, and the custom-kernel C ABI
(`phi/capi/`).  Two TPU-native registration paths:

- `register_custom_op(name, forward, backward=None)`: forward/backward are
  jnp functions — the op dispatches through the eager tape (`apply`), works
  under `to_static` capture and jit, and a provided backward becomes a
  `jax.custom_vjp` rule (the generated GradNode of the reference).
- `custom_op_from_c(lib, symbol, ...)`: wraps a C-ABI kernel built with
  `paddle.utils.cpp_extension.load` via `jax.pure_callback`, so host-native
  kernels participate in jitted programs (the custom CPU-kernel plugin path;
  device kernels belong in Pallas).
"""
from __future__ import annotations

import ctypes
import functools
from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply

_REGISTRY: Dict[str, Callable] = {}


def register_custom_op(name: str, forward: Callable,
                       backward: Optional[Callable] = None):
    """Register op `name`.  forward(*jnp_arrays) -> jnp array (or tuple);
    backward(saved_inputs, grad_out) -> tuple of input grads (or None per
    non-differentiable input).  Returns the Tensor-callable op."""
    if backward is not None:
        @jax.custom_vjp
        def core(*datas):
            return forward(*datas)

        def fwd(*datas):
            return forward(*datas), datas

        def bwd(saved, g):
            grads = backward(saved, g)
            grads = grads if isinstance(grads, (tuple, list)) else (grads,)
            out = []
            for d, gr in zip(saved, grads):
                out.append(jnp.zeros_like(d) if gr is None else gr)
            return tuple(out)

        core.defvjp(fwd, bwd)
        impl = core
    else:
        impl = forward

    def op(*tensors, **kwargs):
        fn = functools.partial(impl, **kwargs) if kwargs else impl
        return apply(name, fn, *tensors)

    op.__name__ = name
    _REGISTRY[name] = op
    return op


def get_custom_op(name: str) -> Callable:
    return _REGISTRY[name]


def custom_op_from_c(lib, symbol: str, out_dtype=None,
                     out_shape_fn: Optional[Callable] = None,
                     name: Optional[str] = None):
    """Wrap a C kernel `void f(const T* in, T* out, int64 n)` (elementwise
    contract, the fake-device test-kernel shape) as a jit-capable op."""
    cfun = getattr(lib, symbol)
    cfun.restype = None
    cfun.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]

    def host_call(x):
        x = np.ascontiguousarray(x)
        out = np.empty_like(x)
        cfun(x.ctypes.data_as(ctypes.c_void_p),
             out.ctypes.data_as(ctypes.c_void_p), x.size)
        return out

    def forward(x):
        shape = out_shape_fn(x.shape) if out_shape_fn else x.shape
        dt = out_dtype or x.dtype
        return jax.pure_callback(
            host_call, jax.ShapeDtypeStruct(shape, dt), x, vmap_method="sequential")

    return register_custom_op(name or symbol, forward)


__all__ = ["register_custom_op", "get_custom_op", "custom_op_from_c"]
