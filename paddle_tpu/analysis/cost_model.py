"""tpu_cost: static HBM / collective / roofline accounting over the serving
jaxprs (reference counterpart: the memory-optimize and inference-analysis
passes that run over the graph before execution — SURVEY "Inference API" +
the `tools/` CI rows).

The quantized-KV and 70B-head roadmap arcs are *memory claims* — "halving KV
bytes doubles live-token capacity", "the two replicated-memory ceilings" —
and until this module nothing in the repo could state, let alone guard, how
many bytes a serving executable actually holds or moves.  Four accounts, all
static (no profiler, no device counters):

- **At-rest HBM** (`engine_at_rest`): every param leaf classified
  sharded-vs-replicated through the SAME `serving_param_specs` layout the mp
  engine places with, plus the page-pool bytes (KVH-sharded under mp).
  Per-device bytes divide the sharded set by mp and keep the replicated set
  whole — which names the embedding/head replication that blocks 70B-class
  configs: any single replicated buffer above the declared ceiling is a
  **JXP006** finding.
- **Peak transient HBM** (`program_cost`): per-eqn liveness over the traced
  jaxpr — a value is live from the eqn that defines it to its last use;
  the peak is the max live-byte watermark.  Donation-aware: an output whose
  (shape, dtype) matches a donated input (the page pool) aliases the input
  buffer and allocates nothing.  This is an XLA-independent *model* (no
  fusion, no buffer reuse beyond liveness), deterministic across backends —
  the budget yardstick; the CLI prints XLA's own `memory_analysis()` numbers
  next to it where available.
- **Collective accounting** (`collective_costs`): the mp programs' psum /
  all-gather / reduce-scatter / collective-permute traffic read from the
  OPTIMIZED HLO (GSPMD inserts Megatron's per-layer all-reduces at compile
  time — they never appear in the jaxpr), with payload bytes from the
  instruction shapes and per-step totals multiplied through while-loop trip
  counts (the layer scan).  A program with collective traffic that the
  registry does not declare, or above its declared per-step byte budget, is
  a **JXP007** finding — single-chip executables must be collective-free.
- **Bytes/flops roofline** (`ProgramCost.predicted_ms`): analytic flops
  (dot_general exact, elementwise = output elems, scan bodies multiplied by
  trip count) over nameplate device specs, against compulsory HBM traffic
  (every input read once + every non-aliased output written once — the
  perfect-fusion lower bound, which for decode is the classic weights-bound
  roofline).  `bench_serve.py` emits `predicted_step_ms` next to the
  measured step time with `model_error` = measured/predicted (tight on TPU
  where the dispatch is device-bound; sanity-bounded only on the CPU smoke,
  where host scheduling dominates).

Budgets (per-executable peak-HBM, the replicated-bytes ceiling, per-
executable collective bytes/step) are declared ONCE in
`analysis/registry.py::SERVE_RESOURCE_BUDGET` alongside the program-count
budget, enforced by `tools/tpu_cost.py --ci`, and are the yardstick the
quantization PR must move (quantized KV pages shrink `pool_bytes`; a
vocab-sharded head moves `wte` out of the replicated set).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .rules import Finding

# ---------------------------------------------------------------------------
# device specs (nameplate numbers for the roofline)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """Peak rates the roofline divides by.  Nameplate numbers — the model
    predicts the *hardware floor* of a dispatch, not a fitted runtime."""
    name: str
    flops_per_s: float          # dense matmul peak (bf16 on TPU)
    hbm_bytes_per_s: float      # HBM bandwidth
    ici_bytes_per_s: float      # per-chip interconnect bandwidth


DEVICE_SPECS: Dict[str, DeviceSpec] = {
    # TPU generations (per chip, bf16 peak / HBM BW / ICI per link-direction)
    "v4": DeviceSpec("tpu-v4", 275e12, 1228e9, 50e9),
    "v5e": DeviceSpec("tpu-v5e", 197e12, 819e9, 45e9),
    "v5p": DeviceSpec("tpu-v5p", 459e12, 2765e9, 90e9),
    "v6e": DeviceSpec("tpu-v6e", 918e12, 1640e9, 90e9),
    # host CPU fallback: order-of-magnitude numbers so the CPU smoke's
    # model_error stays a sanity check, not a fit
    "cpu": DeviceSpec("cpu", 1e11, 2e10, 1e10),
}


# device_kind substrings -> spec row, most specific first (real kind strings
# spell the lite chips out: "TPU v5 lite" / "TPU v6 lite", not "v5e"/"v6e")
_KIND_MATCH = (("v6", "v6e"), ("v5p", "v5p"), ("v5e", "v5e"), ("v5", "v5e"),
               ("v4", "v4"))


def device_spec(device=None) -> DeviceSpec:
    """Spec for `device` (default: jax.devices()[0]) by device_kind
    substring; unknown accelerators fall back to the v5e row (the bench
    fleet's chip), CPU hosts to the cpu row."""
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    platform = (getattr(device, "platform", "") or "").lower()
    for sub, tag in _KIND_MATCH:
        if sub in kind:
            return DEVICE_SPECS[tag]
    if platform == "cpu":
        return DEVICE_SPECS["cpu"]
    return DEVICE_SPECS["v5e"]


# ---------------------------------------------------------------------------
# aval sizes + per-eqn flops
# ---------------------------------------------------------------------------

_EXTENDED_DTYPE_BYTES = 8       # PRNG key leaves: fry keys are 2x uint32


def aval_bytes(aval) -> int:
    """Bytes one materialized value of `aval` occupies (padding ignored)."""
    import numpy as np

    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    try:
        item = np.dtype(aval.dtype).itemsize
    except TypeError:           # extended dtype (jax PRNG key)
        item = _EXTENDED_DTYPE_BYTES
    return n * item


def _prod(xs: Iterable[int]) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def eqn_flops(eqn) -> int:
    """Analytic flop count of one (leaf) eqn: dot_general exact from its
    dimension numbers, everything else one op per output element — the
    standard matmul-dominated model (conv-free codebase)."""
    if eqn.primitive.name == "dot_general":
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval.shape
        rhs = eqn.invars[1].aval.shape
        batch = _prod(lhs[i] for i in lb)
        contract = _prod(lhs[i] for i in lc)
        m = _prod(d for i, d in enumerate(lhs) if i not in lc and i not in lb)
        n = _prod(d for i, d in enumerate(rhs) if i not in rc and i not in rb)
        return 2 * batch * m * n * contract
    return sum(aval_bytes(v.aval) // max(_itemsize(v.aval), 1)
               for v in eqn.outvars if hasattr(v, "aval"))


def _itemsize(aval) -> int:
    import numpy as np
    try:
        return np.dtype(aval.dtype).itemsize
    except TypeError:
        return _EXTENDED_DTYPE_BYTES


def _sub_jaxprs(eqn) -> List[Tuple[object, int]]:
    """(sub-jaxpr, trip multiplier) pairs for a higher-order eqn.  scan
    bodies multiply by `length`; while bodies have unknown trips (counted
    once — the serving programs' only loop is the layer scan).  `cond`
    eqns execute exactly ONE branch, so the walk takes the max over this
    list instead of the sum for them."""
    from jax.core import ClosedJaxpr, Jaxpr

    prim = eqn.primitive.name
    mult = int(eqn.params.get("length", 1)) if prim == "scan" else 1
    subs: List[Tuple[object, int]] = []
    for v in eqn.params.values():
        stack = [v]
        while stack:
            x = stack.pop()
            if isinstance(x, ClosedJaxpr):
                subs.append((x.jaxpr, mult))
            elif isinstance(x, Jaxpr):
                subs.append((x, mult))
            elif isinstance(x, (list, tuple)):
                stack.extend(x)
    return subs


# ---------------------------------------------------------------------------
# per-eqn liveness over a jaxpr
# ---------------------------------------------------------------------------


def _jaxpr_walk(jaxpr, aliased_outs) -> Tuple[int, int, str]:
    """(flops, live-byte peak of body-DEFINED values, label of the peak eqn)
    for one jaxpr.  Invars are excluded (the caller accounts them as
    argument bytes); outvars are included from their defining eqn to the end
    — except `aliased_outs`, which write into a donated input buffer and
    allocate nothing.  Higher-order eqns recurse: their body's peak rides on
    top of the outer live set at that program point."""
    from jax.core import Literal

    eqns = list(jaxpr.eqns)
    last_use: Dict[object, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not isinstance(v, Literal):
                last_use[v] = i
    for v in jaxpr.outvars:
        if not isinstance(v, Literal):
            last_use[v] = len(eqns)

    flops = 0
    live = 0
    peak = 0
    peak_at = ""
    sizes: Dict[object, int] = {}
    for i, eqn in enumerate(eqns):
        subs = _sub_jaxprs(eqn)
        inner_peak = 0
        if subs:
            # cond executes ONE branch: take the worst branch, not the sum
            take_max = eqn.primitive.name == "cond"
            branch_flops = []
            for sub, mult in subs:
                f, p, _ = _jaxpr_walk(sub, frozenset())
                branch_flops.append(f * mult)
                inner_peak = max(inner_peak, p)
            flops += max(branch_flops) if take_max else sum(branch_flops)
        else:
            flops += eqn_flops(eqn)
        alloc = 0
        for v in eqn.outvars:
            sz = 0 if v in aliased_outs else aval_bytes(getattr(v, "aval",
                                                                None))
            sizes[v] = sz
            alloc += sz
        here = live + alloc + inner_peak
        if here > peak:
            peak = here
            peak_at = f"eqn {i}: {eqn.primitive.name}"
        live += alloc
        # free every defined value whose last use is this eqn (or that is
        # never used at all — a dropped output exists only transiently)
        for v in list(eqn.outvars) + [x for x in eqn.invars
                                      if not isinstance(x, Literal)]:
            if v in sizes and last_use.get(v, i) <= i:
                live -= sizes.pop(v)
    return flops, peak, peak_at


# ---------------------------------------------------------------------------
# collective accounting from optimized HLO
# ---------------------------------------------------------------------------

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")
# TPU-optimized modules rewrite collectives into async start/done pairs:
# count the `-start` half only (it carries the payload; matching `-done` too
# would double every transfer), plus the plain synchronous forms CPU emits.
_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<kind>(?:" + "|".join(COLLECTIVE_OPS) + r")(?:-start)?)\(")
_WHILE_RE = re.compile(
    r"\bwhile\(.*condition=%([\w.\-]+), body=%([\w.\-]+)")
_COMPARE_LT_RE = re.compile(
    r"compare\(([^)]*)\)\s*,\s*direction=LT")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")
_TRIP_RE = re.compile(r"s32\[\]\s+constant\((\d+)\)")


def _shape_bytes(shape_text: str, largest_only: bool = False) -> int:
    """Bytes of an HLO result shape ('f32[2,8,64]{2,1,0}' or a tuple).
    `largest_only` takes the biggest component instead of the sum — the
    async `-start` forms return an (operand-alias, result, ...) tuple, and
    summing it would double-count the one transfer."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        item = _HLO_DTYPE_BYTES.get(dtype)
        if item is None:
            continue            # token/opaque element — no payload
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * item)
    if not sizes:
        return 0
    return max(sizes) if largest_only else sum(sizes)


@dataclasses.dataclass
class CollectiveOp:
    """One collective instruction in the optimized module: `payload_bytes`
    is the per-device operand footprint of ONE execution; `multiplier` is
    the enclosing loop trip product (the layer scan), so
    `payload_bytes * multiplier` is this instruction's per-step traffic."""
    kind: str
    shape: str
    payload_bytes: int
    multiplier: int

    @property
    def bytes_per_step(self) -> int:
        return self.payload_bytes * self.multiplier


def _split_computations(hlo_text: str) -> Dict[str, List[str]]:
    """computation name -> its instruction lines.  HLO text opens each
    computation at column 0 (`%name (...) {` / `ENTRY %name (...) {`) and
    closes with a column-0 `}`."""
    comps: Dict[str, List[str]] = {}
    cur: Optional[str] = None
    for line in hlo_text.splitlines():
        if not line.startswith(" "):
            s = line.strip()
            if s.endswith("{"):
                head = s[:-1].strip()
                if head.startswith("ENTRY"):
                    cur = "ENTRY"
                else:
                    cur = head.split()[0].lstrip("%") if head else None
                if cur:
                    comps[cur] = []
            elif s == "}":
                cur = None
            continue
        if cur is not None:
            comps[cur].append(line)
    return comps


def collective_costs(hlo_text: str) -> List[CollectiveOp]:
    """Every collective instruction in an optimized HLO module, with payload
    bytes and the while-loop trip multiplier of its enclosing computation.

    Trip counts come from the paired condition computation's
    `compare(iv, constant(N)), direction=LT` bound; a condition that does
    not parse contributes multiplier 1 (an under-count, never a phantom)."""
    comps = _split_computations(hlo_text)

    # condition computation -> trip count, read from the constant OPERAND of
    # the LT compare (not just any constant in the computation — folded
    # constants would otherwise yield a wrong or zero multiplier); clamped
    # to >= 1 so a misparse can only under-count, never erase traffic
    trips: Dict[str, int] = {}
    for name, lines in comps.items():
        body = "\n".join(lines)
        m = _COMPARE_LT_RE.search(body)
        if not m:
            continue
        bound = None
        for op in _OPERAND_NAME_RE.findall(m.group(1)):
            dm = re.search(r"%" + re.escape(op) +
                           r"\s*=\s*s32\[\]\s+constant\((\d+)\)", body)
            if dm:
                bound = int(dm.group(1))
        if bound is None:
            dm = _TRIP_RE.search(body)      # legacy fallback
            bound = int(dm.group(1)) if dm else None
        if bound is not None:
            trips[name] = max(bound, 1)

    # propagate multipliers along while edges from ENTRY
    mult: Dict[str, int] = {name: 1 for name in comps}
    edges: List[Tuple[str, str, int]] = []      # (enclosing, body, trip)
    for name, lines in comps.items():
        for line in lines:
            m = _WHILE_RE.search(line)
            if m:
                cond, body = m.group(1), m.group(2)
                edges.append((name, body, trips.get(cond, 1)))
    for _ in range(len(edges) + 1):             # fixed point (loops nest)
        changed = False
        for enclosing, body, trip in edges:
            want = mult.get(enclosing, 1) * trip
            if mult.get(body, 1) != want:
                mult[body] = want
                changed = True
        if not changed:
            break

    out: List[CollectiveOp] = []
    for name, lines in comps.items():
        for line in lines:
            m = _COLLECTIVE_RE.search(line)
            if m:
                is_start = m.group("kind").endswith("-start")
                out.append(CollectiveOp(
                    m.group("kind").removesuffix("-start"),
                    m.group("shape").strip(),
                    _shape_bytes(m.group("shape"), largest_only=is_start),
                    mult.get(name, 1)))
    return out


# ---------------------------------------------------------------------------
# per-program cost
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ProgramCost:
    """Static cost account of one serving executable.  All byte fields are
    model units (traced aval bytes, no XLA padding): `peak_bytes` =
    argument bytes + the liveness watermark of program-defined values
    (donation-aliased outputs allocate nothing); `hbm_min_bytes` is the
    compulsory-traffic floor the roofline divides by."""
    name: str
    flops: int
    arg_bytes: int
    out_bytes: int
    alias_bytes: int            # outputs aliasing donated inputs
    temp_peak_bytes: int        # liveness watermark of defined values
    peak_bytes: int             # arg_bytes + temp_peak_bytes
    peak_at: str
    collectives: Optional[List[CollectiveOp]] = None    # None = not compiled
    xla_temp_bytes: Optional[int] = None    # XLA memory_analysis, if compiled

    @property
    def hbm_min_bytes(self) -> int:
        return self.arg_bytes + self.out_bytes - self.alias_bytes

    @property
    def collective_bytes(self) -> int:
        return sum(c.bytes_per_step for c in self.collectives or ())

    def predicted_ms(self, spec: DeviceSpec, mp: int = 1) -> float:
        """Roofline step time: max(compute, HBM) + collective transfer.
        Under mp the flop/byte work divides across chips (the traced shapes
        are global); collective payloads are already per-device."""
        compute_s = self.flops / mp / spec.flops_per_s
        memory_s = self.hbm_min_bytes / mp / spec.hbm_bytes_per_s
        ici_s = self.collective_bytes / spec.ici_bytes_per_s
        return (max(compute_s, memory_s) + ici_s) * 1e3

    def to_json(self) -> Dict[str, object]:
        d = {
            "name": self.name, "flops": self.flops,
            "arg_bytes": self.arg_bytes, "out_bytes": self.out_bytes,
            "alias_bytes": self.alias_bytes,
            "temp_peak_bytes": self.temp_peak_bytes,
            "peak_bytes": self.peak_bytes, "peak_at": self.peak_at,
            "hbm_min_bytes": self.hbm_min_bytes,
        }
        if self.collectives is not None:
            d["collective_bytes_per_step"] = self.collective_bytes
            d["collectives"] = [dataclasses.asdict(c)
                                for c in self.collectives]
        if self.xla_temp_bytes is not None:
            d["xla_temp_bytes"] = self.xla_temp_bytes
        return d


def program_cost(name: str, fn, args, *, compile_collectives: bool = False
                 ) -> ProgramCost:
    """Trace `fn(*args)` (a jitted callable; ShapeDtypeStructs are fine) and
    account it.  Donation is read from the traced pjit eqn itself — the same
    source of truth JXP002 audits — so the cost and the donation audit
    cannot disagree.  `compile_collectives=True` additionally runs the XLA
    compile and reads collective traffic + XLA's own temp-byte number from
    the optimized module (skipped on the bench path, where an extra compile
    would perturb the program-count stats)."""
    import jax
    from jax.core import Literal

    closed = jax.make_jaxpr(fn)(*args)
    body = closed.jaxpr
    consts = closed.consts
    donated = ()
    for eqn in closed.jaxpr.eqns:
        if eqn.primitive.name == "pjit":
            sub = eqn.params["jaxpr"]
            body, consts = sub.jaxpr, sub.consts
            donated = eqn.params.get("donated_invars", ())
            break

    arg_bytes = sum(aval_bytes(v.aval) for v in body.invars)
    arg_bytes += sum(aval_bytes(c) for c in consts)   # consts carry shape/dtype
    out_bytes = sum(aval_bytes(getattr(v, "aval", None))
                    for v in body.outvars if not isinstance(v, Literal))

    # donation aliasing: each donated invar signature absorbs ONE matching
    # output — that output writes in place and allocates nothing
    donated_sigs: List[Tuple[tuple, str]] = []
    for d, v in zip(donated, body.invars):
        if d:
            donated_sigs.append((tuple(v.aval.shape), str(v.aval.dtype)))
    aliased = set()
    alias_bytes = 0
    invars = set(body.invars)
    for v in body.outvars:
        if isinstance(v, Literal) or v in invars or v in aliased:
            continue
        sig = (tuple(v.aval.shape), str(v.aval.dtype))
        if sig in donated_sigs:
            donated_sigs.remove(sig)
            aliased.add(v)
            alias_bytes += aval_bytes(v.aval)

    flops, temp_peak, peak_at = _jaxpr_walk(body, frozenset(aliased))

    collectives = None
    xla_temp = None
    if compile_collectives:
        compiled = fn.lower(*args).compile()
        collectives = collective_costs(compiled.as_text())
        try:
            xla_temp = int(compiled.memory_analysis().temp_size_in_bytes)
        except (AttributeError, NotImplementedError):
            xla_temp = None     # backend without memory_analysis support
    return ProgramCost(name, flops, arg_bytes, out_bytes, alias_bytes,
                       temp_peak, arg_bytes + temp_peak, peak_at,
                       collectives, xla_temp)


# ---------------------------------------------------------------------------
# at-rest HBM accounting
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BufferAccount:
    name: str                   # pytree path ("blocks.qkv_w", "wte", "pool.k")
    bytes: int                  # global (unsharded) footprint
    sharded: bool               # divides by mp per device

    def per_device(self, mp: int) -> int:
        return self.bytes // mp if self.sharded else self.bytes


@dataclasses.dataclass
class AtRestAccount:
    """The serving executable set's resident HBM, per device: params split
    by the mp layout they are PLACED with (`serving_param_specs` — the same
    spec tree the engine device_puts at init) plus the KVH-sharded page
    pool.  At mp=1 the classification still runs (sharded = "what tensor
    parallelism would divide"), so mp1-vs-mp2 comparisons read off the same
    account."""
    mp: int
    buffers: List[BufferAccount]

    def _sum(self, sharded: bool, per_device: bool) -> int:
        return sum(b.per_device(self.mp) if per_device else b.bytes
                   for b in self.buffers
                   if b.sharded == sharded and not b.name.startswith("pool."))

    @property
    def param_bytes_sharded(self) -> int:        # global
        return self._sum(True, False)

    @property
    def param_bytes_sharded_per_device(self) -> int:
        return self._sum(True, True)

    @property
    def param_bytes_replicated(self) -> int:     # per device == global
        return self._sum(False, False)

    @property
    def pool_bytes(self) -> int:                 # global
        return sum(b.bytes for b in self.buffers
                   if b.name.startswith("pool."))

    @property
    def pool_bytes_per_device(self) -> int:
        return sum(b.per_device(self.mp) for b in self.buffers
                   if b.name.startswith("pool."))

    @property
    def per_device_bytes(self) -> int:
        return sum(b.per_device(self.mp) for b in self.buffers)

    def replicated_over(self, ceiling: int) -> List[BufferAccount]:
        return [b for b in self.buffers
                if not b.sharded and b.bytes > ceiling]

    def to_json(self) -> Dict[str, object]:
        return {
            "mp": self.mp,
            "param_bytes_sharded": self.param_bytes_sharded,
            "param_bytes_sharded_per_device":
                self.param_bytes_sharded_per_device,
            "param_bytes_replicated": self.param_bytes_replicated,
            "pool_bytes": self.pool_bytes,
            "pool_bytes_per_device": self.pool_bytes_per_device,
            "per_device_bytes": self.per_device_bytes,
            "top_replicated": [dataclasses.asdict(b) for b in sorted(
                (b for b in self.buffers if not b.sharded),
                key=lambda b: -b.bytes)[:4]],
        }


def _spec_is_sharded(spec) -> bool:
    return any(e is not None for e in (spec or ()))


def params_at_rest(params, config, mp: int = 1) -> List[BufferAccount]:
    """One BufferAccount per param leaf, classified through
    `serving_param_specs` — the layout `LLMEngine(mp=N)` actually places."""
    import jax
    from jax.sharding import PartitionSpec

    from ..parallel.hybrid import serving_param_specs

    specs = serving_param_specs(config, params)
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    spec_leaves = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, PartitionSpec))[0]
    by_path = {jax.tree_util.keystr(p): s for p, s in spec_leaves}
    out = []
    for path, leaf in leaves:
        key = jax.tree_util.keystr(path)
        name = key.replace("['", ".").replace("']", "").lstrip(".")
        out.append(BufferAccount(name, aval_bytes(leaf),
                                 _spec_is_sharded(by_path.get(key))))
    return out


def engine_at_rest(engine) -> AtRestAccount:
    """At-rest account of a live LLMEngine: its params (classified by the
    serving layout) + its page pool (KVH-sharded under mp)."""
    buffers = params_at_rest(engine.params, engine.config, engine.mp)
    for k, v in engine._pool.items():
        buffers.append(BufferAccount(f"pool.{k}", aval_bytes(v), True))
    return AtRestAccount(max(engine.mp, 1), buffers)


# ---------------------------------------------------------------------------
# engine-level costing (the bench hook)
# ---------------------------------------------------------------------------


def engine_step_cost(engine, *, compile_collectives: Optional[bool] = None
                     ) -> ProgramCost:
    """Cost of the engine's decode-side program (fused `serve_step_paged`,
    or the legacy decode under `fuse=False`) at the ENGINE's own shapes,
    traced with abstract inputs carrying the engine's REAL shardings — no
    dispatch, no transfer, and the program-count stats stay untouched
    (the compile, when taken, goes through the jit wrapper's lower(),
    outside the `_AotCache` dispatch cache).

    `compile_collectives` defaults to `engine.mp > 1`: the mp program's
    per-layer all-reduces only exist in the compiled module, and the
    roofline's ICI term needs them — the same account `tools/tpu_cost.py`
    prints, so the bench JSON and the CLI cannot disagree.  Single-chip
    engines skip the compile (nothing to collect)."""
    import jax
    import numpy as np

    def sds(a, sh=None):
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)

    B = engine.cache.num_slots
    P = engine.cache.max_pages_per_slot
    repl = engine._repl_sharding
    if engine._param_shardings is not None:
        params = jax.tree_util.tree_map(sds, engine.params,
                                        engine._param_shardings)
    else:
        params = jax.tree_util.tree_map(sds, engine.params)
    pool = {k: sds(v, engine._pool_sharding)
            for k, v in engine._pool.items()}
    def host(shape, dtype=np.int32):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=repl)

    fn = getattr(engine._decode_fn, "_jit", engine._decode_fn)
    if engine.fused:
        args = (params, host((B, engine._fused_T)), pool, host((B, P)),
                host((B,)), host((B,)), sds(engine._key, repl),
                host((B,), np.bool_))
    else:
        args = (params, host((B,)), pool, host((B, P)), host((B,)),
                sds(engine._key, repl), host((B,), np.bool_))
    if compile_collectives is None:
        compile_collectives = engine.mp > 1
    return program_cost("serve.step", fn, args,
                        compile_collectives=compile_collectives)


# ---------------------------------------------------------------------------
# budget enforcement (tools/tpu_cost.py --ci + tests)
# ---------------------------------------------------------------------------


def _basename(target_name: str) -> str:
    """'serve.mp2.fused_step' -> 'fused_step' (budget keys are per
    executable; the mp tag picks the budget table)."""
    return target_name.split(".")[-1]


def audit_resources(targets, at_rest: AtRestAccount, budget,
                    *, compile_collectives: bool = True
                    ) -> Tuple[List[ProgramCost], List[Finding]]:
    """Run the full account over `targets` ((name, fn, args, kw) rows, the
    `jaxpr_checks.serving_targets` shape) against `budget`
    (`registry.SERVE_RESOURCE_BUDGET`-shaped dict).  Returns the per-program
    costs and the findings:

    - JXP006: a replicated at-rest buffer above the declared ceiling
      (only meaningful at mp > 1 — replication is free on one chip);
    - JXP007: collective traffic in a program with no declared budget, or
      above its declared per-step bytes;
    - JXP008: a program's modeled peak HBM above its declared budget.
    """
    findings: List[Finding] = []
    costs: List[ProgramCost] = []

    ceiling = budget.get("replicated_bytes_ceiling")
    if ceiling is not None and at_rest.mp > 1:
        for b in at_rest.replicated_over(ceiling):
            findings.append(Finding(
                "JXP006", "<at-rest>", 0, 0,
                f"replicated buffer `{b.name}` is {b.bytes} bytes on EVERY "
                f"chip (ceiling {ceiling}) — this is the replicated-memory "
                f"ceiling that blocks 70B-class configs; shard it (e.g. "
                f"vocab-shard the embedding/head) or raise the declared "
                f"ceiling with the math that justifies it"))

    coll_budget: Dict[str, int] = budget.get("collective_bytes_per_step", {})
    peak_budget: Dict[str, int] = budget.get("peak_hbm_bytes", {})
    for name, fn, args, _kw in targets:
        cost = program_cost(name, fn, args,
                            compile_collectives=compile_collectives)
        costs.append(cost)
        path = f"<cost:{name}>"
        if cost.collectives:
            allowed = coll_budget.get(name)
            total = cost.collective_bytes
            if allowed is None:
                kinds = sorted({c.kind for c in cost.collectives})
                findings.append(Finding(
                    "JXP007", path, 0, 0,
                    f"undeclared collective traffic: {total} bytes/step "
                    f"({', '.join(kinds)}) in a program with no "
                    f"collective_bytes_per_step entry in "
                    f"analysis/registry.py — declare it or remove the "
                    f"collective"))
            elif total > allowed:
                findings.append(Finding(
                    "JXP007", path, 0, 0,
                    f"collective traffic {total} bytes/step exceeds the "
                    f"declared budget {allowed} — a reshard/allgather crept "
                    f"into the step program"))
        cap = peak_budget.get(_basename(name), {}).get(f"mp{at_rest.mp}") \
            if isinstance(peak_budget.get(_basename(name)), dict) \
            else peak_budget.get(_basename(name))
        if cap is not None and cost.peak_bytes > cap:
            findings.append(Finding(
                "JXP008", path, 0, 0,
                f"modeled peak HBM {cost.peak_bytes} bytes exceeds the "
                f"declared budget {cap} — the step program holds more "
                f"live bytes than the serving memory plan allows"))
    return costs, findings


def run_cost_checks(include_mp: bool = True, mp=(2, 4),
                    budget=None) -> Tuple[Dict[int, Dict[str, object]],
                                          List[Finding]]:
    """The CI entry: audit the registry-declared serving executables (same
    tiny engines as the jaxpr checks) at mp1 (+ each requested mp degree with
    enough devices — the default covers mp2 AND mp4, the mesh size where the
    vocab-shard win compounds) against `registry.SERVE_RESOURCE_BUDGET`.
    `mp` accepts an int or a sequence of degrees.  Returns ({mp: report},
    all findings)."""
    import jax

    from .jaxpr_checks import (_build_engine, quantized_targets,
                               serving_targets)
    from . import registry

    if budget is None:
        budget = registry.SERVE_RESOURCE_BUDGET
    findings: List[Finding] = []
    reports: Dict[int, Dict[str, object]] = {}
    passes = [1]
    if include_mp:
        for m in ((mp,) if isinstance(mp, int) else tuple(mp)):
            if len(jax.devices()) >= m and m not in passes:
                passes.append(m)
    spec = device_spec()
    for m in passes:
        # ONE fused engine serves both the at-rest account and the audit
        # targets (plus the legacy pair serving_targets needs) — same
        # instance, so the two accounts cannot diverge
        eng, _ = _build_engine(m)
        leg, _ = _build_engine(m, fuse=False)
        at_rest = engine_at_rest(eng)
        costs, fs = audit_resources(serving_targets(m, engines=(eng, leg)),
                                    at_rest, budget)
        findings.extend(fs)
        # JXP009: the UNIFIED host pool (preempt="swap" victim parking +
        # the kv_tier spilled-prefix store, one swap_pool_pages ceiling) is
        # sized, not traced — its declared bound is audited exactly, once
        # per mesh pass (host memory does not shard: the bound is per host)
        host_cap = budget.get("host_pool_bytes")
        host_bytes = eng.host_pool_bytes()
        if host_cap is not None and host_bytes > host_cap:
            findings.append(Finding(
                "JXP009", "<at-rest>", 0, 0,
                f"unified host pool bound {host_bytes} bytes exceeds the "
                f"declared host_pool_bytes budget {host_cap} — size "
                f"swap_pool_pages down (it caps swap parking AND spilled "
                f"prefix pages) or raise the budget with the host memory "
                f"math that justifies it"))
        # ---- quantized serving pass (ISSUE-11): the int8 engine at the
        # SAME pool geometry, audited against its own declared yardstick —
        # the quantization win must show up here before any TPU run -------
        qeng, _ = _build_engine(m, weight_dtype="int8", kv_dtype="int8")
        q_at_rest = engine_at_rest(qeng)
        q_budget = dict(budget)
        q_ceiling = budget.get("replicated_bytes_ceiling_int8")
        if q_ceiling is not None:
            # tightened JXP006 ceiling for the quantized engine: a fp-width
            # embedding re-materializing in the quantized at-rest account
            # is a regression the fp ceiling would never see
            q_budget["replicated_bytes_ceiling"] = q_ceiling
        q_costs, q_fs = audit_resources(
            quantized_targets(m, engine=qeng), q_at_rest, q_budget)
        findings.extend(q_fs)
        costs.extend(q_costs)
        pool_ratio = at_rest.pool_bytes / max(q_at_rest.pool_bytes, 1)
        min_ratio = budget.get("quantized_pool_min_ratio")
        if min_ratio is not None and pool_ratio < min_ratio:
            findings.append(Finding(
                "JXP010", "<at-rest>", 0, 0,
                f"int8 KV pool at-rest bytes shrink only {pool_ratio:.2f}x "
                f"vs the fp pool at the same geometry (declared floor "
                f"{min_ratio}x) — the quantized pool stopped paying for "
                f"itself (a scale lane widened, or pages re-materialized at "
                f"fp width)"))
        q_pool_cap = budget.get("quantized_pool_bytes")
        if q_pool_cap is not None and q_at_rest.pool_bytes > q_pool_cap:
            findings.append(Finding(
                "JXP010", "<at-rest>", 0, 0,
                f"int8 KV pool at-rest bytes {q_at_rest.pool_bytes} exceed "
                f"the declared quantized_pool_bytes budget {q_pool_cap}"))
        # the quantization win is measured on the WHOLE param account: with
        # the embedding/head vocab-sharded, the replicated remainder is just
        # the norm/bias vectors (identical either way, plus tiny fp32 scale
        # leaves on the int8 side), so replicated-only comparison would
        # false-positive on a correct build
        q_total = q_at_rest.param_bytes_sharded \
            + q_at_rest.param_bytes_replicated
        fp_total = at_rest.param_bytes_sharded + at_rest.param_bytes_replicated
        if q_total >= fp_total:
            findings.append(Finding(
                "JXP010", "<at-rest>", 0, 0,
                f"int8 weights do not reduce the at-rest param account "
                f"({q_total} vs fp {fp_total} bytes) — the quantized "
                f"weights are not actually stored int8"))
        q_host_cap = budget.get("host_pool_bytes_int8")
        q_host_bytes = qeng.host_pool_bytes()
        if q_host_cap is not None and q_host_bytes > q_host_cap:
            findings.append(Finding(
                "JXP009", "<at-rest>", 0, 0,
                f"int8 unified host pool bound {q_host_bytes} bytes exceeds "
                f"the declared host_pool_bytes_int8 budget {q_host_cap} — "
                f"int8 pages must park as int8, not re-widened fp"))
        reports[m] = {
            "at_rest": at_rest.to_json(),
            "at_rest_quantized": q_at_rest.to_json(),
            "quantized_pool_ratio": round(pool_ratio, 3),
            "host_pool_bytes": host_bytes,
            "host_pool_bytes_int8": q_host_bytes,
            # predicted_ms computed HERE through ProgramCost.predicted_ms so
            # the CLI report and the bench JSON share one roofline formula
            "programs": [dict(c.to_json(),
                              predicted_ms=round(c.predicted_ms(spec, mp=m),
                                                 4))
                         for c in costs],
        }
    return reports, findings
