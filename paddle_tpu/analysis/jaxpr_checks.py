"""tpu_lint level 2: jaxpr audits of the serving executables.

Level 1 reads source; this level reads what jax will actually compile.  Each
registry-declared serving executable is traced with abstract inputs
(`jax.make_jaxpr` — tracing only, no XLA compile) and the closed jaxpr is
audited:

- **JXP001** transfer primitives inside the program (`device_put`, host
  callbacks): a serving step must be pure device compute — an embedded
  transfer is a hidden per-dispatch host round-trip that no AST pattern can
  see once it hides behind a helper.
- **JXP002** donation mismatch, both directions: every declared-donated
  buffer (the KV page pool) must actually arrive donated in the pjit params
  (else XLA double-buffers the pool every step), and declared-persistent
  buffers (params, reused across calls) must NOT be donated (else the second
  dispatch reads freed memory).  Any other large undeclared input that is
  not donated is flagged too.
- **JXP003** dtype upcasts: float64 anywhere in the program (a leaked Python
  float / np.float64 under x64) or an upcast `convert_element_type` to f64.
- **JXP004** (mp mode) missing sharding constraint: the tensor-parallel
  executables must pin their output pool layout (`pin_pool`'s
  `with_sharding_constraint`) — without the pin, GSPMD-inferred output
  shardings drift between calls and the fixed program set silently forks.
- **JXP005** oversized host-visible output: the fused one-dispatch step
  moved sampling and spec acceptance on device precisely so the per-step
  host fetch is O(B*K) ints — this audit bounds the program's non-donated
  output elements (`host_output_budget`) and flags any float matrix output
  (logits-shaped), so a refactor cannot quietly reintroduce the `[B, V]`
  logits fetch.  Outputs whose (shape, dtype) matches a donated input (the
  in-place page pool) are exempt: they never cross to the host.

`audit_jaxpr` is the reusable core (tests feed it toy jits for
positive/negative pairs); `run_jaxpr_checks` builds tiny CPU engines (the
default fused engine AND the `fuse=False` legacy trio, so the `--no-fuse`
escape hatch stays audited) and checks the real serving set — fused step,
legacy decode/chunk/verify, bucketed prefill, COW copy, and the two
preemption KV-swap copies (swap-out gather / swap-in scatter) — plus an
mp=2 pass when enough devices exist.  The quantized serving engine's fused
step (`quantized_targets`, weight/kv int8) rides the same audit so dequant
cannot smuggle a transfer/upcast/logits-fetch into the one-dispatch step.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

from .rules import Finding

TRANSFER_PRIMITIVES = frozenset({
    "device_put", "pure_callback", "io_callback", "debug_callback",
    "infeed", "outfeed"})

LARGE_LEAF_ELEMS = 1 << 16      # "large" for the undeclared-buffer check


def _iter_eqns(jaxpr):
    """Every eqn in `jaxpr` and its nested sub-jaxprs (pjit bodies, scan/cond
    branches, custom_vjp calls...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _as_jaxprs(v):
                yield from _iter_eqns(sub)


def _as_jaxprs(value):
    from jax.core import ClosedJaxpr, Jaxpr
    if isinstance(value, ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, Jaxpr):
        yield value
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _as_jaxprs(v)


def _arg_paths(args) -> List[str]:
    """Human-readable path per flattened leaf of `args`, aligned with the
    pjit eqn's invar order: 'arg2[k][0]' style."""
    import jax
    leaves_with_path = jax.tree_util.tree_flatten_with_path(tuple(args))[0]
    out = []
    for path, _ in leaves_with_path:
        s = ""
        for i, key in enumerate(path):
            if i == 0:
                s = f"arg{getattr(key, 'idx', key)}"
            else:
                s += jax.tree_util.keystr((key,))
        out.append(s)
    return out


def _under(path: str, prefixes: Sequence[str]) -> bool:
    return any(path == p or path.startswith(p + "[") or
               path.startswith(p + ".") for p in prefixes)


def audit_jaxpr(name: str, fn, args, *, donate_paths: Sequence[str] = (),
                keep_paths: Sequence[str] = (),
                require_sharding_constraint: bool = False,
                host_output_budget: Optional[int] = None,
                large_leaf_elems: int = LARGE_LEAF_ELEMS) -> List[Finding]:
    """Trace `fn(*args)` (a jitted callable) and run every jaxpr check.
    Findings carry the pseudo-path `<jaxpr:name>` — they live in the traced
    program, not on a source line."""
    import jax
    import numpy as np

    path = f"<jaxpr:{name}>"
    findings: List[Finding] = []
    closed = jax.make_jaxpr(fn)(*args)

    # the jitted callable traces to a single pjit eqn carrying the program
    pjit_eqn = None
    for eqn in closed.jaxpr.eqns:
        if eqn.primitive.name == "pjit":
            pjit_eqn = eqn
            break

    # ---- JXP001: transfers inside the program -----------------------------
    for eqn in _iter_eqns(closed.jaxpr):
        if eqn.primitive.name in TRANSFER_PRIMITIVES and eqn is not pjit_eqn:
            findings.append(Finding(
                "JXP001", path, 0, 0,
                f"`{eqn.primitive.name}` primitive inside the program — a "
                f"hidden per-dispatch transfer/host round-trip"))

    # ---- JXP002: donation, both directions --------------------------------
    if pjit_eqn is not None:
        donated = pjit_eqn.params.get("donated_invars", ())
        paths = _arg_paths(args)
        if len(paths) == len(donated):
            for p, d, var in zip(paths, donated, pjit_eqn.invars):
                aval = getattr(var, "aval", None)
                size = int(np.prod(aval.shape)) if aval is not None and \
                    aval.shape else 1
                if _under(p, donate_paths) and not d:
                    findings.append(Finding(
                        "JXP002", path, 0, 0,
                        f"declared-donated buffer `{p}` "
                        f"({aval.str_short() if aval else '?'}) is NOT "
                        f"donated — XLA double-buffers it every dispatch"))
                elif _under(p, keep_paths) and d:
                    findings.append(Finding(
                        "JXP002", path, 0, 0,
                        f"persistent buffer `{p}` IS donated — the next "
                        f"dispatch would read freed memory"))
                elif not d and size >= large_leaf_elems and \
                        not _under(p, keep_paths) and \
                        not _under(p, donate_paths):
                    findings.append(Finding(
                        "JXP002", path, 0, 0,
                        f"large input `{p}` ({aval.str_short()}) neither "
                        f"donated nor declared persistent — copied every "
                        f"dispatch; donate it or register it as kept"))
        elif donate_paths or keep_paths:
            findings.append(Finding(
                "JXP002", path, 0, 0,
                f"cannot align {len(donated)} pjit inputs with "
                f"{len(paths)} argument leaves — donation audit skipped; "
                f"does the traced function close over arrays?"))
    elif donate_paths or keep_paths:
        # the audit must fail CLOSED: if the callable was not actually jitted
        # (make_jaxpr inlined it, no pjit eqn), a declared donation contract
        # cannot be verified and silence would mean CI green while unguarded
        findings.append(Finding(
            "JXP002", path, 0, 0,
            "no pjit eqn in the traced program (callable not jitted?) — "
            "declared donation contract cannot be audited"))

    # ---- JXP003: dtype upcasts --------------------------------------------
    seen_f64 = False
    for eqn in _iter_eqns(closed.jaxpr):
        for v in list(eqn.outvars) + [x for x in eqn.invars
                                      if hasattr(x, "aval")]:
            aval = getattr(v, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt == "float64" and not seen_f64:
                seen_f64 = True
                findings.append(Finding(
                    "JXP003", path, 0, 0,
                    "float64 value inside the program — a Python float / "
                    "np.float64 leaked into the trace (4x the bf16 compute "
                    "budget per element)"))
        if eqn.primitive.name == "convert_element_type":
            new = str(eqn.params.get("new_dtype", ""))
            old = str(getattr(eqn.invars[0].aval, "dtype", "")) \
                if hasattr(eqn.invars[0], "aval") else ""
            if new == "float64" and old in ("float32", "bfloat16"):
                findings.append(Finding(
                    "JXP003", path, 0, 0,
                    f"upcast convert_element_type {old} -> float64 inside "
                    f"the program"))

    # ---- JXP005: oversized host-visible output ----------------------------
    if host_output_budget is not None:
        donated_sigs: List[Tuple[tuple, str]] = []
        if pjit_eqn is not None:
            for d, var in zip(pjit_eqn.params.get("donated_invars", ()),
                              pjit_eqn.invars):
                aval = getattr(var, "aval", None)
                if d and aval is not None:
                    donated_sigs.append((tuple(aval.shape), str(aval.dtype)))
        small_elems = 0
        for aval in closed.out_avals:
            sig = (tuple(aval.shape), str(aval.dtype))
            if sig in donated_sigs:
                # an output shaped exactly like a donated input is the
                # in-place buffer (page pool) riding through — never fetched
                donated_sigs.remove(sig)
                continue
            # extended-dtype-aware floating check: bfloat16 (the TPU serving
            # dtype) must be caught too, and PRNG key dtypes must not crash
            if jax.dtypes.issubdtype(aval.dtype, np.floating) and \
                    len(aval.shape) >= 2:
                findings.append(Finding(
                    "JXP005", path, 0, 0,
                    f"host-visible float output {aval.str_short()} — "
                    f"logits-shaped; the fused step must return O(B*K) int "
                    f"tokens/accept counts, never [B, V] logits"))
            small_elems += int(np.prod(aval.shape)) if aval.shape else 1
        if small_elems > host_output_budget:
            findings.append(Finding(
                "JXP005", path, 0, 0,
                f"host-visible output totals {small_elems} elements (budget "
                f"{host_output_budget}) — the per-step fetch must stay "
                f"O(B*K) ints or the fused step's sync win is gone"))

    # ---- JXP004: sharding constraint under mp -----------------------------
    if require_sharding_constraint:
        n = sum(1 for eqn in _iter_eqns(closed.jaxpr)
                if eqn.primitive.name == "sharding_constraint")
        if n == 0:
            findings.append(Finding(
                "JXP004", path, 0, 0,
                "mp-mode executable has NO sharding_constraint — the output "
                "pool layout is GSPMD-inferred and can drift between calls "
                "(pin it with with_sharding_constraint, see engine.pin_pool)"))
    return findings


# ---------------------------------------------------------------------------
# the real serving targets
# ---------------------------------------------------------------------------


def _build_engine(mp: int, fuse: bool = True, weight_dtype=None,
                  kv_dtype=None):
    import jax

    from ..inference.engine import LLMEngine
    from ..models import gpt as gpt_mod

    cfg = gpt_mod.gpt_tiny(64)
    params = gpt_mod.init_params(cfg, jax.random.key(0))
    return LLMEngine(params, cfg, num_slots=2, page_size=8, max_model_len=64,
                     prefill_chunk=8, spec_len=2, fuse=fuse,
                     weight_dtype=weight_dtype, kv_dtype=kv_dtype,
                     mp=mp if mp > 1 else None), cfg


def serving_targets(mp: int = 1, engines=None
                    ) -> List[Tuple[str, object, tuple, dict]]:
    """(name, jitted fn, example args, audit kwargs) for every serving
    executable, mirroring the engine's own dispatch shapes.  Two engines:
    the default FUSED engine supplies the one-dispatch step (audited under
    JXP001-005 — the host-output budget proves the O(B*K)-int fetch), the
    bucketed cold prefill and the COW copy; a `fuse=False` engine supplies
    the legacy decode/chunk/verify trio so the --no-fuse escape hatch stays
    under the same donation/transfer/dtype discipline.  `engines` injects a
    prebuilt (fused, legacy) pair so callers that also need the engine for
    other accounts (tpu_cost's at-rest pass) build it once."""
    import jax.numpy as jnp

    if engines is not None:
        eng, leg = engines
    else:
        eng, _cfg = _build_engine(mp)
        leg, _ = _build_engine(mp, fuse=False)
    B = eng.cache.num_slots
    P = eng.cache.max_pages_per_slot
    i32 = jnp.int32
    tag = f"mp{mp}." if mp > 1 else ""
    mp_kw = dict(require_sharding_constraint=mp > 1)

    def unwrap(fn):
        return getattr(fn, "_jit", fn)     # _AotCache under mp, jit else

    C = leg.prefill_chunk
    bucket = eng.buckets[0]
    T = leg.spec_len + 1
    Tf = eng._fused_T
    cfgL = eng._pool["k"].shape[0]      # layers: swap staging leading dim
    return [
        (f"serve.{tag}fused_step", unwrap(eng._decode_fn),
         (eng.params, jnp.zeros((B, Tf), i32), eng._pool,
          jnp.zeros((B, P), i32), jnp.zeros((B,), i32),
          jnp.ones((B,), i32), eng._key, jnp.zeros((B,), bool)),
         dict(donate_paths=("arg2",), keep_paths=("arg0",),
              host_output_budget=B * (Tf + 2) + 2, **mp_kw)),
        (f"serve.{tag}decode", unwrap(leg._decode_fn),
         (leg.params, jnp.zeros((B,), i32), leg._pool,
          jnp.zeros((B, P), i32), jnp.zeros((B,), i32), leg._key,
          jnp.zeros((B,), bool)),
         dict(donate_paths=("arg2",), keep_paths=("arg0",), **mp_kw)),
        (f"serve.{tag}chunk_prefill", unwrap(leg._chunk_fn),
         (leg.params, jnp.zeros((1, C), i32), leg._pool,
          jnp.zeros((1, P), i32), jnp.zeros((1,), i32),
          jnp.ones((1,), i32), leg._key, jnp.zeros((1,), bool)),
         dict(donate_paths=("arg2",), keep_paths=("arg0",), **mp_kw)),
        (f"serve.{tag}bucketed_prefill", unwrap(eng._prefill_fn),
         (eng.params, jnp.zeros((1, bucket), i32), eng._pool,
          jnp.zeros((1, bucket // eng.cache.page_size), i32),
          jnp.ones((1,), i32), eng._key, jnp.zeros((1,), bool)),
         dict(donate_paths=("arg2",), keep_paths=("arg0",), **mp_kw)),
        (f"serve.{tag}verify", unwrap(leg._verify_fn),
         (leg.params, jnp.zeros((B, T), i32), leg._pool,
          jnp.zeros((B, P), i32), jnp.zeros((B,), i32),
          jnp.ones((B,), i32)),
         dict(donate_paths=("arg2",), keep_paths=("arg0",), **mp_kw)),
        (f"serve.{tag}cow_copy", unwrap(eng._copy_fn),
         (eng._pool, jnp.zeros((), i32), jnp.ones((), i32)),
         dict(donate_paths=("arg0",), **mp_kw)),
        # preemption KV swap copies: the swap-out gather reads the pool into
        # a standalone buffer (pool NOT donated — it stays live; its output
        # IS a host-bound bulk fetch, so no host_output_budget applies); the
        # swap-in scatter restores in place (pool donated).
        (f"serve.{tag}swap_out", unwrap(eng._swap_out_fn),
         (eng._pool, jnp.zeros((P,), i32)),
         dict(keep_paths=("arg0",), **mp_kw)),
        (f"serve.{tag}swap_in", unwrap(eng._swap_in_fn),
         (eng._pool, jnp.zeros((P,), i32),
          {n: jnp.zeros((cfgL, P) + a.shape[2:], a.dtype)
           for n, a in eng._pool.items()}),
         dict(donate_paths=("arg0",), **mp_kw)),
    ]


def quantized_targets(mp: int = 1, engine=None
                      ) -> List[Tuple[str, object, tuple, dict]]:
    """The int8 serving engine's fused step as an audit target: same JXP001-
    005 discipline as the fp fused step (pool donated, params kept, O(B*K)
    int host output) over a weight_dtype=kv_dtype="int8" engine — dequant
    must not smuggle a transfer, an f64 upcast, a logits-shaped output or an
    undonated pool copy into the program.  `engine` injects a prebuilt
    quantized engine (tpu_cost builds one for the at-rest account anyway)."""
    import jax.numpy as jnp

    qeng = engine
    if qeng is None:
        qeng, _ = _build_engine(mp, weight_dtype="int8", kv_dtype="int8")
    B = qeng.cache.num_slots
    P = qeng.cache.max_pages_per_slot
    i32 = jnp.int32
    tag = f"mp{mp}." if mp > 1 else ""
    Tf = qeng._fused_T
    return [
        (f"serve.{tag}fused_step_int8", getattr(qeng._decode_fn, "_jit",
                                                qeng._decode_fn),
         (qeng.params, jnp.zeros((B, Tf), i32), qeng._pool,
          jnp.zeros((B, P), i32), jnp.zeros((B,), i32),
          jnp.ones((B,), i32), qeng._key, jnp.zeros((B,), bool)),
         dict(donate_paths=("arg2",), keep_paths=("arg0",),
              host_output_budget=B * (Tf + 2) + 2,
              require_sharding_constraint=mp > 1)),
    ]


def run_jaxpr_checks(include_mp: bool = True,
                     mp: int = 2) -> List[Finding]:
    """Audit every serving executable's jaxpr; adds the mp pass when the
    host exposes enough devices (CI forces 8 virtual CPU chips)."""
    import jax

    findings: List[Finding] = []
    passes: List[int] = [1]
    if include_mp and len(jax.devices()) >= mp:
        passes.append(mp)
    for m in passes:
        for name, fn, args, kw in serving_targets(m) + quantized_targets(m):
            findings.extend(audit_jaxpr(name, fn, args, **kw))
    return findings
