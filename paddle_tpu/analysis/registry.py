"""Central compiled-program registry: the declared source-of-truth for every
`jax.jit`/`pjit`/`shard_map` call site in the tree and for the serving
engine's program-count budget.

Three consumers keep each other honest:

- **TPL002** (`tools/tpu_lint.py`): a jit/shard_map call site not declared
  here is a lint failure — new program sources cannot appear silently; a
  declared site with no remaining code is flagged as stale.
- **`tools/check_program_count.py`**: re-measures the live serving program
  counts against `SERVE_PROGRAM_BUDGET[_MP]` below — the budget is declared
  ONCE here, so the runtime guard and the static guard cannot drift apart.
- **`analysis/jaxpr_checks.py`**: level-2 targets reference the serving
  entries' budget buckets when auditing donation/transfer/dtype discipline.
- **`tools/tpu_cost.py`**: re-measures the serving executables' static
  resource account (at-rest HBM, liveness peak, collective bytes/step)
  against `SERVE_RESOURCE_BUDGET` below — memory and communication budgets
  are declared ONCE here, next to the program-count budget they extend.

Granularity is (repo-relative path, enclosing function qualname): one entry
covers every jit call textually inside that function (lambdas fold into their
enclosing def).  That matches how program sources actually cluster — e.g.
`LLMEngine.__init__` builds all five serving executables through one wrapper.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# serving program budget (consumed by tools/check_program_count.py and README)
# ---------------------------------------------------------------------------

# Continuous batching is only viable on TPU because the engine runs a FIXED
# set of executables regardless of traffic shape.  Since the one-dispatch
# refactor the decode side is a SINGLE fused program
# (`models/gpt.py::serve_step_paged`, built through `LLMEngine.__init__`'s
# jit_ wrapper as `_decode_fn`): vanilla decode, spec verify and the
# interleaved prefill chunk all ride it, with sampling and the accept scan on
# device.  The prefill budget covers the cold paths (bucketed one-shot +
# prefix-tail chunk in bucketed mode; zero programs in chunked mode, where
# the chunk rides the fused batch), plus one COW page copy.  The swap budget
# covers the two KV-copy executables — ONE fixed-shape gather
# (`swap_out_pages`, page ids padded to the slot capacity) and ONE scatter
# (`swap_in_pages`) — shared by BOTH host-copy paths: preemption swap
# parking (oversubscription PR) and the KV tier's prefix spill/restore
# (tiering PR), which reuse the same programs so tiering adds ZERO
# executables.  They compile only when a swap or spill actually fires
# (warmed by `warm_swap` on engines that can reach them).
SERVE_PROGRAM_BUDGET: Dict[str, int] = {
    "decode_side_executables": 1,   # THE fused serve_step_paged program
    "prefill_executables": 2,
    "copy_executables": 1,
    "swap_executables": 2,          # preemption swap-out gather + swap-in scatter
    "total_executables": 6,
}

# Per-mesh-config budget under tensor parallelism: the AOT path keeps counts
# exact; the contract since the one-dispatch refactor is decode-side <= 1 at
# EVERY mesh config (the fused program partitions, it does not fork).
SERVE_PROGRAM_BUDGET_MP: Dict[str, int] = {
    "decode_side_executables": 1,
    "prefill_executables": 2,
    "copy_executables": 1,
    "swap_executables": 2,
    "total_executables": 6,
}

# ---------------------------------------------------------------------------
# serving resource budget (consumed by tools/tpu_cost.py --ci and tests)
# ---------------------------------------------------------------------------

# Static HBM/collective ceilings over the SAME tiny audit engines the jaxpr
# checks trace (`jaxpr_checks._build_engine`: gpt_tiny(64), 2 slots, page 8,
# chunk 8, spec 2 — mp1, mp2 AND mp4, the mesh size where the sharded-head
# win compounds).  Units are cost-model bytes (traced aval bytes,
# `analysis/cost_model.py` — deterministic across backends, no XLA padding).
# These are the repo's memory yardstick: the quantized-KV arc shrank the
# pool term, the vocab-sharded-head arc moved `wte` out of the replicated
# set — both show up HERE before any TPU run.
SERVE_RESOURCE_BUDGET: Dict[str, object] = {
    # Per-buffer ceiling on bytes REPLICATED on every chip under mp (JXP006).
    # RATCHETED with the vocab-sharded head (ISSUE-18): `wte` (256 x 64 fp32
    # = 64 KiB, the former ceiling-setter and 70B blocker) now lives in the
    # SHARDED column, and the largest replicated leaf left is a 512 B
    # norm/bias vector — 4096 is 8x headroom over that while any replicated
    # matrix (a re-replicated head at 64 KiB, even the tiny-config wte)
    # fails immediately.  At GPT-3 vocab the retired ceiling was
    # 50304 x D x 2 bytes PER CHIP no matter how large the mesh.
    "replicated_bytes_ceiling": 4_096,
    # Per-executable modeled peak HBM (JXP008): argument bytes + the
    # donation-aware liveness watermark.  Measured 2026-08 at mp1/mp2
    # (fused 689k/762k, decode 676k/750k, chunk 633k/710k, bucketed
    # 607k/681k, verify 680k/753k, cow 82k/152k) + ~25% headroom for jax
    # tracing drift; a real regression (an undonated pool copy, a second
    # materialized logits buffer) blows through 25% immediately.
    "peak_hbm_bytes": {
        "fused_step": 950_000,
        "decode": 940_000,
        "chunk_prefill": 890_000,
        "bucketed_prefill": 850_000,
        "verify": 940_000,
        "cow_copy": 190_000,
        # preemption KV swap copies (oversubscription PR): the gather holds
        # pool + one slot-capacity staging buffer; the scatter holds pool +
        # two staging uploads.  Measured 2026-08 (swap_out 139k/172k mp1/mp2,
        # swap_in 139k/213k; collective-free at mp2 — the page axis is
        # unsharded) + ~30% headroom.
        "swap_out": 230_000,
        "swap_in": 280_000,
        # quantized fused step (weight+kv int8): int8 at-rest args shrink
        # the account to LESS than the fp program — measured 2026-08
        # 322k/345k mp1/mp2 (+25% headroom).  A dequant that materializes
        # the whole fp weight stack (instead of one block inside the layer
        # scan) or an fp KV pool copy blows through this immediately.
        "fused_step_int8": 430_000,
    },
    # Per-executable collective bytes per step (JXP007), keyed by the FULL
    # target name: only the mp>1 programs may communicate at all.  The
    # declared traffic per step is (a) the Megatron row-parallel all-reduces
    # (proj + fc2, 2/layer), (b) the vocab-parallel embed's ONE hidden-sized
    # psum (ISSUE-18 — the price of never holding a replicated wte), and
    # (c) the sharded-argmax merge: one (value, index) scalar PAIR per row
    # (pmax + pmin, 2 x 4 B x rows) — NEVER logits-sized.  Measured 2026-08
    # on the audit config (L=2, f32): fused 20608 B/step (16384 layer
    # psums + 4096 embed psum + 128 argmax pair), decode 2576,
    # chunk/bucketed 10248, verify 7728 — budgets are measured + ~20%
    # headroom, so a logits-wide allgather (32 KiB at even this toy vocab)
    # fails immediately.  Collective payloads are LOGICAL bytes, so mp2 and
    # mp4 share one measured account (per-chip shards halve, the summed
    # traffic does not).  An mp1 program with ANY collective, or an mp>1
    # program absent from this table, is undeclared traffic and fails CI.
    "collective_bytes_per_step": {
        "serve.mp2.fused_step": 24_576,
        # dequant is chip-local (scales shard with their weights/pages), so
        # the quantized fused step carries exactly the fp program's traffic
        "serve.mp2.fused_step_int8": 24_576,
        "serve.mp2.decode": 4_096,
        "serve.mp2.chunk_prefill": 12_288,
        "serve.mp2.bucketed_prefill": 12_288,
        "serve.mp2.verify": 10_240,
        # the mp4 audit pass (same logical payloads, see above)
        "serve.mp4.fused_step": 24_576,
        "serve.mp4.fused_step_int8": 24_576,
        "serve.mp4.decode": 4_096,
        "serve.mp4.chunk_prefill": 12_288,
        "serve.mp4.bucketed_prefill": 12_288,
        "serve.mp4.verify": 10_240,
    },
    # UNIFIED host-pool ceiling (JXP009): the bound
    # `LLMEngine.host_pool_bytes()` declares for EVERYTHING parked in host
    # memory — preempt="swap" victim KV AND the kv_tier spilled-prefix store
    # share this one `swap_pool_pages` budget (disk-tier pages are
    # off-budget; intake admission and the preempt decision both count
    # against it via `PagedKVCache.host_pool_room`).  Audit engine: 8 pages
    # x (2 layers x 8 tok x 4 KVH x 16 hd x 4 B x k+v) = 64 KiB, checked
    # exactly (the host pool is sized, not traced).  The yardstick for the
    # quantized-KV arc: halving page bytes must halve this ceiling too.
    "host_pool_bytes": 65_536,
    # ---- quantized serving (weight_dtype="int8" + kv_dtype="int8") --------
    # The quantized audit engine (same gpt_tiny(64) geometry, 9-page pool) is
    # accounted alongside the fp one each pass; all four numbers below are
    # the declared side of the ISSUE-11 acceptance bars:
    # - int8 replicated per-buffer ceiling (JXP006 on the quantized at-rest
    #   account): ratcheted with the fp ceiling (ISSUE-18) — wte_q/wte_scale
    #   shard with the vocab axis, so the quantized replicated remainder is
    #   the same 512 B norm/bias vectors plus tiny fp32 scale leaves.  A
    #   quantized embedding re-materializing replicated (16 KiB int8, 64 KiB
    #   fp) blows through 4096 immediately.
    "replicated_bytes_ceiling_int8": 4_096,
    # - int8 pool at-rest ceiling + minimum shrink ratio (JXP010): the fp
    #   pool is 72 KiB (2 x [2,9,8,4,16] f32), the int8 pool 22.5 KiB
    #   (int8 pages + per-token f32 scale lanes) — measured ratio 3.2x,
    #   declared floor 2.0x (the "~2x smaller at kv_dtype=int8, same pool
    #   geometry" acceptance bar, met with margin at fp32; bf16 pools land
    #   at ~1.9x which is why the floor is 2.0 on the f32 audit config, not
    #   a universal constant).
    "quantized_pool_bytes": 24_576,
    "quantized_pool_min_ratio": 2.0,
    # - int8 unified host-pool ceiling (JXP009 extended): int8 pages park
    #   as int8 — spill and swap alike — 8 pages x 2.5 KiB/page (k+v int8 +
    #   scale lanes) = 20 KiB, checked exactly like the fp bound (3.2x
    #   under the fp 64 KiB).
    "host_pool_bytes_int8": 20_480,
}


# ---------------------------------------------------------------------------
# serving SLO + health thresholds (consumed by inference/health.py, the obs
# server's /healthz and tools/check_metrics.py)
# ---------------------------------------------------------------------------

# The engine's health evaluation folds the live signal plane — multi-window
# SLO burn rates, pool pressure, admission saturation (timeout/reject rates),
# preemption rate, steady-state recompile anomalies — into ONE
# ok/degraded/overloaded state with per-signal reasons, against the targets
# declared HERE (and only here: the /healthz probe, stats()["health"], the
# `engine_health` gauge and the health tests all read this dict).  The
# numbers are the audit/CPU-smoke config's yardstick, same convention as
# SERVE_RESOURCE_BUDGET; a real deployment re-declares them for its traffic.
SERVE_SLO: Dict[str, object] = {
    # deadline-attainment target: the SLO the burn rates measure against.
    # Burn = (windowed miss fraction) / (1 - target): burn 1.0 consumes the
    # error budget exactly as fast as allowed, >1 is on track to violate.
    "deadline_attainment_target": 0.99,
    # latency bounds on the engine-side lifecycle histograms (p99, ms):
    # crossing one degrades health (the engine still serves; a router should
    # prefer other replicas).  Sized for the CPU-smoke/audit config — a cold
    # compile inside a first request's TTFT legitimately trips it.
    "ttft_p99_ms": 2000.0,
    "tpot_p99_ms": 500.0,
    # device KV pool pressure (pages in use / usable pages) at or above this
    # fraction degrades health: admission is about to stall and preemption
    # is imminent — the router should stop sending work here first.
    "pressure_ceiling": 0.95,
    # multi-window burn: page only when the FAST window burns hot while the
    # SLOW window confirms it is not a blip (the classic two-window rule).
    # Labels index inference.metrics.RATE_WINDOWS.
    "burn_window_fast": "1m",
    "burn_window_slow": "5m",
    "burn_degraded": 1.0,       # either window at 1.0 = budget-speed burn
    "burn_overloaded": 10.0,    # fast >= 10 x budget AND slow confirming
    # preemption churn (preemptions/s over the fast 10s window): sustained
    # preemption means live tokens exceed pool capacity — degraded at the
    # first trickle, overloaded when victims are evicted every second.
    "preempt_rate_degraded": 0.1,
    "preempt_rate_overloaded": 1.0,
    # admission saturation: ANY deadline timeout or intake rejection inside
    # the fast 10s window degrades; timeouts at or above this rate mean the
    # engine is shedding load faster than it serves — overloaded.
    "timeout_rate_overloaded": 1.0,
    # acceptable band for measured/predicted step time (the live roofline
    # drift gauge).  Wide because it must hold on CPU-smoke hosts where
    # dispatch overhead dominates; on TPU the ratio sits near 1 and a
    # tighter operational band belongs in the deployment's alert config.
    # Excursions count alert TRANSITIONS (roofline_drift_alerts counter),
    # they do not fold into engine_health (a slow host is not an overload).
    "roofline_drift_band": (0.02, 50.0),
}

# ---------------------------------------------------------------------------
# serving-bench perf floors (consumed by tools/check_bench.py --ci)
# ---------------------------------------------------------------------------

# The serving-bench trajectory (`BENCH_SERVE.jsonl`, appended by
# bench_serve.py / tools/check_bench.py) is CI-enforced the same way the
# HBM/program budgets are: floors declared ONCE here, re-measured on a fresh
# CPU-smoke bench run by `tools/check_bench.py --ci`.  Wall-clock numbers on
# a shared CI box swing +-10%, so the floors bind the DETERMINISTIC side of
# the bench (byte parity, dispatch counts, the stamp-count tracing account)
# tightly and the wall-clock ratios loosely.
SERVE_PERF_FLOORS: Dict[str, object] = {
    "schema_version": 5,
    # every parity flag a bench run reports must be True — byte-exact greedy
    # parity is the one bar noise cannot excuse (kv_tier_parity: tier
    # restores must be bit-exact vs the --no-kv-tier re-prefill;
    # fleet_parity: routing a session stream across dp replicas must emit
    # the same tokens as one engine serving it alone; disagg_parity: the
    # prefill->store->decode handoff AND the engine-restart restore must
    # both reproduce the colocated single-engine stream byte-for-byte)
    "parity_flags": ("fuse_parity", "spec_parity", "oversubscribe_parity",
                     "tracing_parity", "kv_tier_parity", "fleet_parity",
                     "disagg_parity"),
    # the one-dispatch claim in numbers: a fused busy step dispatches
    # exactly ONE decode-side program — tied to the program budget above so
    # the two guards cannot drift apart
    "dispatches_per_step_max": float(
        SERVE_PROGRAM_BUDGET["decode_side_executables"]),
    # fused-vs-unfused tokens/s ratio.  The fused win is a TPU claim
    # (dispatch overhead is what fusion removes); on this shared CPU-smoke
    # box the measured ratio hovers ~0.89-1.46 run-over-run depending on
    # load and mode, so the floor only catches a COLLAPSE (a fused path
    # suddenly dispatching extra work), not the win itself — byte parity
    # and dispatches_per_step carry the deterministic side of the claim.
    "fused_speedup_min": 0.8,
    # the always-on tracing plane's deterministic stamp-count x unit-cost
    # account (bench `tracing_overhead_measured`) must stay under 2%
    "tracing_overhead_max": 0.02,
    # roofline sanity: model_error (measured/predicted step ms) must exist
    # and be a positive finite ratio.  On TPU it is meaningful (~1-3); the
    # CPU smoke is host-scheduling-bound so the ceiling only catches a
    # broken prediction (zero, negative, or absurd), not slow hosts.
    "model_error_max": 1.0e5,
    # a bench run that emitted nothing has no trajectory row to contribute
    "tokens_per_sec_min": 1.0,
    # the KV-tier capacity claim, deterministic on any multi-turn row that
    # ran the --no-kv-tier comparison: returning sessions must re-prefill
    # at most half the tokens the drop-on-evict baseline pays (the measured
    # CPU smoke sits ~0.7-0.85; token counts are scheduling-exact, so this
    # floor is noise-free)
    "returning_prefilled_drop_min": 0.5,
    # the affinity-routing claim (dp fleet PR), deterministic on any
    # `--replicas > 1` row: the returning-turn prefix-hit odds ratio
    # (1 + affinity_hit) / (1 + round_robin_hit) on the identical session
    # stream must be >= 1 — cache-aware routing never hits LESS than the
    # cache-blind round-robin baseline (the measured CPU smoke sits ~1.45;
    # hit rates are token-count-exact, so this floor is noise-free).  The
    # TTFT side of the A/B is wall-clock and stays report-only.
    "affinity_prefix_hit_ratio_min": 1.0,
    # the disaggregation handoff ceiling (disagg rows): p99 wall latency of
    # a prefill->store->decode handoff (prefill submit through decode index
    # refresh).  Wall-clock on a shared CPU smoke, so the ceiling is set to
    # catch only a collapse (a handoff path that re-prefills, blocks on a
    # lock, or re-reads the whole store); measured CPU-smoke handoffs sit
    # in the tens of ms.  disagg_parity carries the deterministic side.
    "handoff_p99_ms_max": 5000.0,
    # the vocab-sharded-head claim (schema v5, deterministic — leaf-shape
    # arithmetic, no wall clock): on any mp >= 2 row the per-device
    # replicated param bytes must sit STRICTLY below the fp `wte` size the
    # row also reports — i.e. the embedding/head genuinely left the
    # replicated column (a re-replicated head makes replicated >= wte
    # by definition).  The JXP006 ratchet enforces the same invariant on
    # the audit engines; this floor enforces it on every bench row.
    "replicated_below_wte": True,
}


@dataclasses.dataclass(frozen=True)
class ProgramSource:
    """One declared jit/shard_map site cluster.

    `budget` names the SERVE_PROGRAM_BUDGET bucket these programs count
    against (None for non-serving sources: training steps, export paths,
    test-only helpers).  `note` says what compiles there and why its count is
    bounded — the registry doubles as the program-inventory document."""
    path: str                           # repo-relative, '/'-separated
    qualname: str                       # enclosing def ("" = module level)
    budget: Optional[str] = None
    note: str = ""


PROGRAM_SOURCES: Tuple[ProgramSource, ...] = (
    # ---- serving engine (the budgeted set) --------------------------------
    ProgramSource(
        "paddle_tpu/inference/engine.py", "_AotCache.__init__",
        budget="total_executables",
        note="mp-mode AOT wrapper: one lower().compile() per signature; the "
             "wrapper IS how the mp program count stays exact"),
    ProgramSource(
        "paddle_tpu/inference/engine.py", "LLMEngine.__init__",
        budget="total_executables",
        note="the serving executables built through the jit_ wrapper, fixed "
             "shapes per engine.  Fused (default): serve_step_paged — THE "
             "one-dispatch step (decode + verify + interleaved chunk in one "
             "[B, max(K+1, chunk)] batch, on-device sampling/acceptance, "
             "O(B*K)-int host output) — plus the cold prefill paths, the "
             "COW copy and the two KV-swap copies (swap_out gather / "
             "swap_in scatter — shared by preemption swap parking AND the "
             "KV tier's prefix spill/restore, compiled when either path "
             "fires); fuse=False additionally builds the legacy decode/"
             "chunk/verify trio (A/B baseline, outside the default budget)"),
    # ---- model core -------------------------------------------------------
    ProgramSource(
        "paddle_tpu/models/gpt.py", "generate",
        note="legacy one-shot generate: one program per (config, B, Tp, "
             "max_new) shape, LRU-bounded by GENERATE_CACHE_MAX"),
    ProgramSource(
        "paddle_tpu/models/gpt.py", "prefill_paged",
        note="bucketed prefill's dense flash attention shard_mapped over mp "
             "(inside the serving prefill executable, no standalone program)"),
    ProgramSource(
        "paddle_tpu/models/gpt.py", "_embed",
        note="vocab-parallel serving embed: masked local take + psum over "
             "the vocab-sharded wte (inside the serving executables, no "
             "standalone program)"),
    ProgramSource(
        "paddle_tpu/models/gpt.py", "sharded_argmax",
        note="sharded argmax merge over vocab-sharded logits — per-chip "
             "(value, global index) pair + pmax/pmin tie-break (inside the "
             "serving executables, no standalone program)"),
    ProgramSource(
        "paddle_tpu/models/gpt.py", "sample_token",
        note="sharded temperature/top-k pick: local top-k + k*mp all-gather "
             "threshold + gumbel-argmax merge (inside the serving "
             "executables, no standalone program)"),
    # ---- parallel trainers ------------------------------------------------
    ProgramSource(
        "paddle_tpu/parallel/ring_attention.py", "shard_map_compat",
        note="the repo-wide shard_map wrapper (new-API/old-API fallback); "
             "call sites through it register at their own qualnames"),
    ProgramSource(
        "paddle_tpu/parallel/ring_attention.py", "ring_attention",
        note="context-parallel ring attention body"),
    ProgramSource(
        "paddle_tpu/parallel/hybrid.py", "_moe_ffn_ep",
        note="expert-parallel MoE body (one program inside the train step)"),
    ProgramSource(
        "paddle_tpu/parallel/hybrid.py", "_cp_loss",
        note="context-parallel loss shard_map (ring attention lane)"),
    ProgramSource(
        "paddle_tpu/parallel/hybrid.py", "_vp_embed",
        note="vocab-parallel embedding shard_map"),
    ProgramSource(
        "paddle_tpu/parallel/hybrid.py", "_vp_ce",
        note="vocab-parallel cross-entropy shard_map"),
    ProgramSource(
        "paddle_tpu/parallel/hybrid.py", "_pp_loss",
        note="pipeline-parallel GPipe loop shard_map"),
    ProgramSource(
        "paddle_tpu/parallel/hybrid.py", "HybridParallelTrainer.__init__",
        note="param/optimizer init programs (one each per trainer)"),
    ProgramSource(
        "paddle_tpu/parallel/hybrid.py", "HybridParallelTrainer._build_step",
        note="THE train step: one program per trainer config"),
    ProgramSource(
        "paddle_tpu/parallel/hybrid.py", "HybridParallelTrainer.eval_loss",
        note="jitted eval loss, compiled once (test_eval_loss_jitted_once)"),
    # ---- kernels ----------------------------------------------------------
    ProgramSource(
        "paddle_tpu/incubate/kernels/paged_attention.py",
        "paged_attention_decode_mp",
        note="decode paged attention per-shard under the serving mp mesh"),
    ProgramSource(
        "paddle_tpu/incubate/kernels/paged_attention.py",
        "paged_prefill_attention_mp",
        note="prefill/verify paged attention per-shard under mp"),
    # ---- export / static-graph paths --------------------------------------
    ProgramSource(
        "paddle_tpu/jit/api.py", "save",
        note="StableHLO export: one program per saved InputSpec signature"),
    ProgramSource(
        "paddle_tpu/jit/program.py", "ConcreteProgram.__init__",
        note="dy2static captured forward"),
    ProgramSource(
        "paddle_tpu/jit/program.py", "ConcreteProgram.run",
        note="dy2static captured backward (built on first .backward)"),
    ProgramSource(
        "paddle_tpu/static/__init__.py", "save_inference_model",
        note="static-mode export program"),
    # ---- distributed facades ----------------------------------------------
    ProgramSource(
        "paddle_tpu/distributed/communication/ops.py", "_replicated_jit",
        note="eager collective facade: one tiny program per op/mesh"),
    ProgramSource(
        "paddle_tpu/distributed/auto_parallel/engine.py", "Engine.predict",
        note="auto-parallel predictor forward"),
)

_BY_KEY: Dict[Tuple[str, str], ProgramSource] = {
    (s.path, s.qualname): s for s in PROGRAM_SOURCES}


def lookup(path: str, qualname: str) -> Optional[ProgramSource]:
    """The declared source covering a jit site at (path, enclosing qualname).
    Falls back to walking qualname prefixes so a site inside a nested def
    (`LLMEngine.__init__.decode_impl`) is covered by its enclosing entry."""
    parts = qualname.split(".") if qualname else []
    for i in range(len(parts), -1, -1):
        hit = _BY_KEY.get((path, ".".join(parts[:i])))
        if hit is not None:
            return hit
    return None


def for_path(path: str) -> List[ProgramSource]:
    return [s for s in PROGRAM_SOURCES if s.path == path]
