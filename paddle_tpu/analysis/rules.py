"""tpu_lint rule engine: rule catalog, findings, suppression comments.

Reference lineage: the reference repo ships a `tools/` CI layer of custom
static checks (op-registry audits, API-signature guards, lint passes over
generated kernels — SURVEY §tools) because framework invariants rot silently.
Ours guard the serving/training hot-path discipline instead of op registries:
one fixed program set, no stray host<->device syncs, donated hot buffers,
no shape-dependent Python branches inside traced code.

Rules are small classes over a prebuilt per-file index (`visitor.FileContext`)
— the expensive work (scope table, call graph, device-value taint) happens
once per file in `visitor.py`; each rule is a thin query over it.

Suppression syntax (same line or the line directly above the finding):

    # tpu-lint: disable=TPL001 -- reason why this sync is intentional
    # tpu-lint: disable=TPL001,TPL005 -- shared reason
    # tpu-lint: disable-file=TPL004 -- file-wide, e.g. generated code

A reason (the `-- ...` tail) is mandatory: a disable comment without one is
itself reported as LINT000 — an unexplained suppression is exactly the silent
rot this tool exists to stop.
"""
from __future__ import annotations

import dataclasses
import io
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple

# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Finding:
    """One diagnostic: rule code + location + message.  `suppressed` findings
    are kept (they appear in --json output and suppression-audit tooling) but
    do not fail the run."""
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def format(self) -> str:
        tag = f" [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}{tag}"

    def to_json(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

_DISABLE_RE = re.compile(
    r"#\s*tpu-lint:\s*disable(?P<filewide>-file)?\s*=\s*"
    r"(?P<codes>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
    r"(?:\s*--\s*(?P<reason>.*\S))?")


class Suppressions:
    """Per-file suppression table parsed from `# tpu-lint: disable=...`
    comments.  A line-scoped disable covers findings on its own line and the
    line directly below (comment-above style); `disable-file=` covers the
    whole file."""

    def __init__(self, source: str):
        self.by_line: Dict[int, Tuple[List[str], str]] = {}
        self.file_wide: Dict[str, str] = {}
        self.malformed: List[int] = []      # disable comments missing a reason
        # tokenize so only REAL comments count: a docstring or string literal
        # that merely quotes the disable syntax (this module's own docs, a
        # test fixture) must not become a live suppression
        try:
            comments = [(t.start[0], t.string) for t in
                        tokenize.generate_tokens(io.StringIO(source).readline)
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = list(enumerate(source.splitlines(), start=1))
        for i, text in comments:
            m = _DISABLE_RE.search(text)
            if not m:
                continue
            codes = [c.strip().upper() for c in m.group("codes").split(",")]
            reason = (m.group("reason") or "").strip()
            if not reason:
                self.malformed.append(i)
                continue                    # an unexplained disable disables nothing
            if m.group("filewide"):
                for c in codes:
                    self.file_wide[c] = reason
            else:
                self.by_line[i] = (codes, reason)

    def lookup(self, rule: str, line: int) -> Optional[str]:
        """The reason string when `rule` is suppressed at `line`, else None."""
        if rule in self.file_wide:
            return self.file_wide[rule]
        for ln in (line, line - 1):
            entry = self.by_line.get(ln)
            if entry and (rule in entry[0] or "ALL" in entry[0]):
                return entry[1]
        return None

    def apply(self, findings: Iterable[Finding]) -> List[Finding]:
        out = []
        for f in findings:
            reason = self.lookup(f.rule, f.line)
            if reason is not None:
                f.suppressed = True
                f.reason = reason
            out.append(f)
        return out


# ---------------------------------------------------------------------------
# rule base + catalog
# ---------------------------------------------------------------------------


class Rule:
    """One static check.  Subclasses set `code`/`title`/`rationale` and
    implement `check(ctx)` over a `visitor.FileContext`."""
    code = "TPL000"
    title = ""
    rationale = ""

    def check(self, ctx) -> Iterable[Finding]:  # pragma: no cover - interface
        raise NotImplementedError

    def finding(self, ctx, node, message: str) -> Finding:
        return Finding(self.code, ctx.relpath, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


class HostSyncRule(Rule):
    """TPL001: scalarization sync on a device value in step()-reachable code.

    `.item()`, `float()`, `int()`, or an implicit `bool()` (an `if`/`while`
    test) on a value produced by a device dispatch blocks the host per call
    AND round-trips one scalar at a time — the pattern that turns a
    one-dispatch engine step into a sync-per-slot crawl.  Bulk fetches
    (`np.asarray`) are TPL005's business."""
    code = "TPL001"
    title = "host-sync-in-hot-path"
    rationale = "scalar device->host syncs serialize the engine step loop"

    def check(self, ctx):
        for ev in ctx.hot_sync_events:
            if ev.kind == "scalarize":
                yield self.finding(
                    ctx, ev.node,
                    f"host sync `{ev.what}` on device value in "
                    f"step()-reachable `{ev.func}` — batch the fetch "
                    f"(np.asarray inside a RecordEvent span) or keep the "
                    f"value on device")
            elif ev.kind == "implicit_bool":
                yield self.finding(
                    ctx, ev.node,
                    f"implicit bool() of device value in step()-reachable "
                    f"`{ev.func}` — a hidden blocking sync; fetch explicitly "
                    f"first")


class UnregisteredJitRule(Rule):
    """TPL002: `jax.jit`/`pjit`/`shard_map` call site not declared in
    `analysis/registry.py`.

    The serving program budget (`tools/check_program_count.py`) is only
    enforceable if every place that can mint a compiled program is known.  A
    new jit site must be declared — with which budget bucket it compiles into
    — or it is invisible to the budget until it blows it in production.
    Also flags stale registry entries (declared site no longer in the code),
    so the registry cannot drift from reality in either direction."""
    code = "TPL002"
    title = "unregistered-program-source"
    rationale = "every compiled-program source must be budgeted centrally"

    def check(self, ctx):
        seen = set()
        for site in ctx.jit_sites:
            entry = ctx.registry.lookup(ctx.relpath, site.qualname)
            if entry is not None:
                seen.add((ctx.relpath, entry.qualname))
            else:
                yield self.finding(
                    ctx, site.node,
                    f"{site.kind} call site `{ctx.relpath}::"
                    f"{site.qualname or '<module>'}` not declared in "
                    f"analysis/registry.py — declare it (with its program "
                    f"budget bucket) so check_program_count stays exhaustive")
        for entry in ctx.registry.for_path(ctx.relpath):
            if (ctx.relpath, entry.qualname) not in seen:
                yield Finding(
                    self.code, ctx.relpath, 1, 0,
                    f"stale registry entry: `{entry.qualname or '<module>'}` "
                    f"is declared as a program source but no jit/shard_map "
                    f"call site remains there — remove it from "
                    f"analysis/registry.py")


class MissingDonateRule(Rule):
    """TPL003: jitted function taking a large persistent buffer
    (pool/params/opt_state-style parameter) without `donate_argnums`.

    Without donation XLA must materialize input and output copies of the
    buffer every dispatch — for a KV page pool that doubles serving memory
    and adds a copy to every engine step.  (Deliberately non-donated buffers
    — e.g. params reused across calls — get a suppression with the reason.)"""
    code = "TPL003"
    title = "undonated-hot-buffer"
    rationale = "non-donated large buffers double memory and copy per step"

    BIG_PARAMS = frozenset({"params", "pool", "state", "opt_state", "kv",
                            "kv_cache", "cache", "buffers", "weights"})

    def check(self, ctx):
        for site in ctx.jit_sites:
            if site.kind != "jit" or site.fn_params is None:
                continue
            big = sorted(self.BIG_PARAMS & set(site.fn_params))
            if big and site.donate is False:
                yield self.finding(
                    ctx, site.node,
                    f"jit of `{site.fn_name}({', '.join(site.fn_params)})` "
                    f"has large-buffer param(s) {big} but no donate_argnums "
                    f"— the buffer is copied every dispatch")


class TracedBranchRule(Rule):
    """TPL004: Python `if`/`while` on a traced value inside a jitted function.

    Tracing specializes the branch on the concrete value, silently compiling
    one program per value seen — the exact per-shape/per-value recompile the
    fixed-program-set engine design forbids.  Branch on static config, use
    `jnp.where`/`lax.cond`, or hoist the decision to the host."""
    code = "TPL004"
    title = "python-branch-on-traced-value"
    rationale = "value-dependent Python branches multiply compiled programs"

    def check(self, ctx):
        for br in ctx.traced_branches:
            yield self.finding(
                ctx, br.node,
                f"Python `{br.stmt}` on traced parameter `{br.param}` of "
                f"jitted `{br.func}` — use jnp.where/lax.cond or make the "
                f"argument static")


class UntimedFetchRule(Rule):
    """TPL005: blocking device->host fetch outside a RecordEvent span.

    `engine.trace()` (PR 5) reconstructs where a serving step spends its
    time from the host-phase spans; a bulk fetch (`np.asarray` /
    `jax.device_get` on a device value) that blocks outside any span is
    invisible to that timeline — the trace shows an idle host while the
    device sync eats the step budget."""
    code = "TPL005"
    title = "untimed-blocking-fetch"
    rationale = "unspanned device syncs are invisible to the step trace"

    def check(self, ctx):
        for ev in ctx.hot_sync_events:
            if ev.kind == "fetch":
                yield self.finding(
                    ctx, ev.node,
                    f"blocking device fetch `{ev.what}` outside a "
                    f"RecordEvent span in step()-reachable `{ev.func}` — "
                    f"wrap it in the engine's sample-sync span so the step "
                    f"trace can see the stall")


class BareExceptDeviceRule(Rule):
    """TPL006: `except Exception`/bare `except` around device code.

    The PR-5 `execs()` bug class: a broad handler around a jax call converts
    a real defect (bad sharding, Mosaic compile failure, donated-buffer
    reuse) into a silently-wrong fallback.  Catch the specific exceptions the
    guarded degradation is FOR, or suppress with the reason."""
    code = "TPL006"
    title = "bare-except-around-device-code"
    rationale = "broad handlers around device calls hide real defects"

    def check(self, ctx):
        for h in ctx.broad_device_handlers:
            yield self.finding(
                ctx, h.node,
                f"`except {h.caught}` around device call(s) "
                f"({', '.join(sorted(h.device_calls)[:3])}) — narrow to the "
                f"exceptions the fallback is for")


class DoubleBufferHazardRule(Rule):
    """TPL007: page-state mutation before harvesting the in-flight batch.

    Under double-buffered scheduling (`fuse=True` + `double_buffer=True`)
    the fused dispatch of step *n* is still writing KV when the host runs
    between steps — its result is parked in `self._inflight` until the next
    harvest.  A public entry point that frees or reassigns page-table/
    refcount state (release/allocate, `lengths[...]`/`page_table[...]`
    stores) while that batch is in flight hands pages to a new owner whose
    bookkeeping the in-flight result will then corrupt — the invariant
    `LLMEngine.abort()` protects by harvesting FIRST.  The rule keys on the
    class publishing `_inflight` and on a `_harvest` call (directly or via a
    callee) preceding the first mutation."""
    code = "TPL007"
    title = "double-buffer-hazard"
    rationale = "page mutation with a dispatch in flight corrupts harvests"

    def check(self, ctx):
        for hz in ctx.db_hazards:
            yield self.finding(
                ctx, hz.node,
                f"public `{hz.method}` mutates page state ({hz.what}) "
                f"without first harvesting the in-flight batch — call "
                f"self._harvest() (or gate on self._inflight) before "
                f"touching page tables/refcounts")


class SuppressionReasonRule(Rule):
    """LINT000: a `# tpu-lint: disable=` comment without a `-- reason`."""
    code = "LINT000"
    title = "suppression-without-reason"
    rationale = "unexplained suppressions defeat the audit trail"

    def check(self, ctx):
        for line in ctx.suppressions.malformed:
            yield Finding(
                self.code, ctx.relpath, line, 0,
                "tpu-lint disable comment without a `-- reason`; the "
                "suppression is ignored until a reason is given")


AST_RULES: Tuple[Rule, ...] = (
    HostSyncRule(), UnregisteredJitRule(), MissingDonateRule(),
    TracedBranchRule(), UntimedFetchRule(), BareExceptDeviceRule(),
    DoubleBufferHazardRule(), SuppressionReasonRule(),
)

# jaxpr-level checks (implemented in jaxpr_checks.py) share the catalog so
# --list-rules documents both levels in one table
JAXPR_RULE_TABLE: Tuple[Tuple[str, str, str], ...] = (
    ("JXP001", "transfer-inside-program",
     "device_put/callback primitives inside a serving executable"),
    ("JXP002", "donation-mismatch",
     "declared-donated buffer not donated, or large undeclared buffer "
     "copied per dispatch"),
    ("JXP003", "dtype-upcast",
     "float64 avals or f32->f64 / bf16->f64 upcasts inside the program"),
    ("JXP004", "missing-sharding-constraint",
     "mp-mode executable without a sharding_constraint pinning its output "
     "layout"),
    ("JXP005", "oversized-host-output",
     "serving-step output exceeds the O(B*K)-int budget or is logits-shaped "
     "— reintroduces the per-step [B, V] host fetch the fused step removed"),
    # resource budgets (implemented in cost_model.py, enforced by tpu_cost)
    ("JXP006", "oversized-replicated-buffer",
     "an mp at-rest buffer replicated on every chip exceeds the declared "
     "ceiling — the embedding/head replication that blocks 70B configs"),
    ("JXP007", "undeclared-collective",
     "collective traffic (psum/all-gather/reduce-scatter) undeclared in "
     "SERVE_RESOURCE_BUDGET or above its per-step byte budget"),
    ("JXP008", "peak-hbm-over-budget",
     "a serving program's modeled peak HBM (donation-aware jaxpr liveness) "
     "exceeds its declared per-executable budget"),
    ("JXP009", "swap-pool-over-budget",
     "the engine's host-side KV swap pool bound exceeds the declared "
     "swap_pool_bytes budget — preemption parking must stay host-memory "
     "accountable"),
)


def rule_table() -> List[Tuple[str, str, str]]:
    """(code, title, rationale) for every shipped rule, both levels."""
    rows = [(r.code, r.title, r.rationale) for r in AST_RULES]
    rows += list(JAXPR_RULE_TABLE)
    return rows
