"""paddle_tpu.analysis — static analysis that proves the engine's
dispatch/sync discipline (reference counterpart: the `tools/` CI-check layer
of custom op-registry/API/lint guards; SURVEY §tools).

Two levels, one CLI (`tools/tpu_lint.py`):

- **Level 1 (AST, stdlib-only)** — `visitor.py` + `rules.py`: host-sync
  taint in step()-reachable code, unregistered jit/shard_map sites (checked
  against `registry.py`, the declared program source-of-truth), missing
  donation, Python branches on traced values, untimed device fetches, broad
  excepts around device code.  Per-rule inline suppressions with mandatory
  reasons.
- **Level 2 (jaxpr)** — `jaxpr_checks.py`: traces the registry-declared
  serving executables with abstract inputs and audits the closed jaxprs for
  transfer primitives, donation mismatches, dtype upcasts and (mp) missing
  sharding constraints.
- **Resource accounting** — `cost_model.py` (CLI `tools/tpu_cost.py`):
  static HBM/collective/roofline accounts over the same serving executables
  — at-rest sharded/replicated/pool bytes per device (JXP006 replicated
  ceiling), donation-aware jaxpr-liveness peak (JXP008), collective
  bytes/step from the optimized HLO (JXP007), the host swap-pool bound
  (JXP009, fp + int8), and a bytes/flops roofline — against
  `registry.SERVE_RESOURCE_BUDGET`.  The quantized serving engine
  (weight/kv int8) is accounted each pass against its own declared
  yardstick (tightened replicated ceiling, pool-shrink floor — JXP010).
"""
from __future__ import annotations

from .rules import (AST_RULES, Finding, Rule, Suppressions, rule_table)
from .visitor import (FileContext, ModuleIndex, iter_python_files,
                      run_ast_checks)
from . import registry

__all__ = ["AST_RULES", "Finding", "Rule", "Suppressions", "rule_table",
           "FileContext", "ModuleIndex", "iter_python_files",
           "run_ast_checks", "registry", "run_jaxpr_checks",
           "run_cost_checks"]


def run_jaxpr_checks(*args, **kwargs):
    """Lazy facade over `jaxpr_checks.run_jaxpr_checks` — level 2 imports
    jax; level 1 must stay importable without it."""
    from .jaxpr_checks import run_jaxpr_checks as impl
    return impl(*args, **kwargs)


def run_cost_checks(*args, **kwargs):
    """Lazy facade over `cost_model.run_cost_checks` (imports jax)."""
    from .cost_model import run_cost_checks as impl
    return impl(*args, **kwargs)
