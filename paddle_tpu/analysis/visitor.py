"""tpu_lint level-1 engine: per-file AST index + the rule driver.

One parse per file builds everything the rules query:

- a scope table of every function/method (qualnames, params, nested defs)
  and a same-file call graph, from which **step()-reachability** is computed
  (the "hot path" TPL001/TPL005 guard: everything the engine's `step()` can
  reach on the host side);
- a **device-value taint** pass over hot functions: values produced by
  device dispatches (`jnp.*`/`jax.*` calls, `*_fn`/`*_impl` executables) are
  tracked through assignments; scalarizations (`int()`, `.item()`, implicit
  `bool()`) and bulk fetches (`np.asarray`, `jax.device_get`) of tainted
  values become sync events, annotated with whether they sit inside a
  `RecordEvent`/`_span` context;
- every **jit/shard_map call site** (incl. local aliases like the engine's
  `jit_ =` wrapper and `functools.partial(jax.jit, ...)` decorators), with
  the jitted function resolved to its def where possible so donation and
  traced-branch checks see real parameter lists;
- broad `except` handlers whose try body contains device calls.

Everything is stdlib-only (ast + tokenize-free): level 1 must lint a file in
milliseconds with no jax import.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .rules import AST_RULES, Finding, Suppressions

# functions whose bodies (and same-file transitive callees) are "hot":
# the serving engine's scheduler loop
HOT_ROOTS = frozenset({"step"})

# calls that produce device values (taint sources)
_DEVICE_CALL_RE = re.compile(
    r"(^|\.)((jax|jnp)\.)|(_fn|_impl)$|(^|\.)pallas_call$")
# calls that fetch a device value to the host (bulk, legitimate, must be
# spanned) vs. scalarize it (per-element, TPL001)
_FETCH_FUNCS = frozenset({"np.asarray", "np.array", "numpy.asarray",
                          "numpy.array", "jax.device_get"})
_SCALARIZE_FUNCS = frozenset({"float", "int", "bool", "complex"})
# span context managers: entering one of these `with` blocks times the sync
_SPAN_CALL_RE = re.compile(r"(^|\.)(_span|RecordEvent)$")

_JIT_FUNCS = frozenset({"jax.jit", "jit", "pjit", "jax.pjit", "_AotCache"})
_SHARD_RE = re.compile(r"(^|\.)(shard_map|shard_map_compat)$")

# parameter names treated as static/config (never traced data) in TPL004
_STATIC_PARAM_NAMES = frozenset({"self", "cls", "cfg", "config", "mesh",
                                 "axis_names", "in_specs", "out_specs"})


def dotted_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass
class FunctionInfo:
    qualname: str
    node: ast.AST                       # FunctionDef | AsyncFunctionDef | Lambda
    params: List[str]
    scope: str                          # enclosing qualname ("" = module)
    calls: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class JitSite:
    node: ast.Call
    kind: str                           # "jit" | "shard_map"
    qualname: str                       # enclosing function (lambdas stripped)
    fn_name: str                        # display name of the jitted callable
    fn_params: Optional[List[str]]      # resolved parameter list, if known
    fn_node: Optional[ast.AST]          # resolved def/lambda, if known
    donate: Optional[bool]              # has donate_argnums? None = unknown


@dataclasses.dataclass
class SyncEvent:
    node: ast.AST
    kind: str                           # "scalarize" | "fetch" | "implicit_bool"
    what: str                           # e.g. "int(...)", "np.asarray(...)"
    func: str                           # hot function qualname
    spanned: bool                       # inside a RecordEvent/_span `with`


@dataclasses.dataclass
class TracedBranch:
    node: ast.AST
    stmt: str                           # "if" | "while"
    param: str
    func: str


@dataclasses.dataclass
class BroadHandler:
    node: ast.AST
    caught: str                         # "Exception" | "<bare>"
    device_calls: Set[str]


@dataclasses.dataclass
class DoubleBufferHazard:
    node: ast.AST                       # the mutation (or its call site)
    method: str                         # public entry-point qualname
    what: str                           # description of the page-state write


def _params_of(node: ast.AST) -> List[str]:
    a = node.args
    names = [x.arg for x in getattr(a, "posonlyargs", [])] + \
            [x.arg for x in a.args] + [x.arg for x in a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _strip_lambdas(qualname: str) -> str:
    """Normalize `<lambda>`/`<locals>` segments so a jit call inside a lambda
    registers under its enclosing named function."""
    parts = [p for p in qualname.split(".")
             if p not in ("<lambda>", "<locals>")]
    return ".".join(parts)


class _Indexer(ast.NodeVisitor):
    """Single walk: scope table + per-function call lists + jit-ish sites."""

    def __init__(self):
        self.functions: Dict[str, FunctionInfo] = {}
        self.stack: List[str] = []              # qualname segments
        self.fn_stack: List[FunctionInfo] = []
        self.raw_jit_calls: List[Tuple[ast.Call, str]] = []  # (node, qualname)
        # (decorator node, decorated FunctionDef, its qualname)
        self.raw_jit_decorators: List[Tuple[ast.AST, ast.AST, str]] = []
        self.jit_aliases: Set[str] = set()      # names assigned jit-wrapper lambdas
        self.module_body: List[ast.stmt] = []

    # -- scope bookkeeping ---------------------------------------------------
    def _qual(self, name: str) -> str:
        return ".".join(self.stack + [name]) if self.stack else name

    def visit_Module(self, node):
        self.module_body = node.body
        self.generic_visit(node)

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_function(self, node, name):
        qn = self._qual(name)
        info = FunctionInfo(qn, node, _params_of(node), ".".join(self.stack))
        # first def wins on duplicate qualnames (overloads by `if` are rare)
        self.functions.setdefault(qn, info)
        # decorator-style jit sites (@jax.jit / @jax.jit(...) /
        # @functools.partial(jax.jit, ...)) — these never appear as a plain
        # jit *call* with the function as an argument, so collect them here
        # or TPL002/TPL003 are blind to them
        for dec in node.decorator_list:
            if self._is_jit_decorator(dec):
                self.raw_jit_decorators.append(
                    (dec, node, _strip_lambdas(qn)))
        self.stack.append(name)
        self.fn_stack.append(info)
        self.generic_visit(node)
        self.fn_stack.pop()
        self.stack.pop()

    @staticmethod
    def _is_jit_decorator(dec: ast.AST) -> bool:
        if isinstance(dec, ast.Call):
            fd = dotted_name(dec.func) or ""
            if fd in _JIT_FUNCS:
                return True             # @jax.jit(static_argnums=...)
            if fd.split(".")[-1] == "partial" and dec.args:
                return (dotted_name(dec.args[0]) or "") in _JIT_FUNCS
            return False
        return (dotted_name(dec) or "") in _JIT_FUNCS   # bare @jax.jit

    def visit_FunctionDef(self, node):
        self._visit_function(node, node.name)

    def visit_AsyncFunctionDef(self, node):
        self._visit_function(node, node.name)

    def visit_Lambda(self, node):
        self.stack.append("<lambda>")
        self.generic_visit(node)
        self.stack.pop()

    # -- calls ---------------------------------------------------------------
    def visit_Call(self, node):
        d = dotted_name(node.func)
        if d is not None and self.fn_stack:
            self.fn_stack[-1].calls.append(d)
        if d is not None:
            base = d.split(".")[-1]
            if d in _JIT_FUNCS or base in ("_AotCache",) or \
                    d in self.jit_aliases or _SHARD_RE.search(d):
                self.raw_jit_calls.append(
                    (node, _strip_lambdas(".".join(self.stack))))
        self.generic_visit(node)

    def visit_Assign(self, node):
        # detect jit-wrapper aliases: `jit_ = (lambda fn, donate: jax.jit(...))
        # if mp else (lambda ...)` — calls through the alias are jit sites
        src = ast.dump(node.value)
        if "jax" in src and ("'jit'" in src or "_AotCache" in src):
            has_jit = any(
                isinstance(c, ast.Call) and
                (dotted_name(c.func) in _JIT_FUNCS or
                 (dotted_name(c.func) or "").split(".")[-1] == "_AotCache")
                for c in ast.walk(node.value))
            if has_jit:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and \
                            isinstance(node.value, (ast.Lambda, ast.IfExp)):
                        self.jit_aliases.add(tgt.id)
        self.generic_visit(node)


class ModuleIndex:
    """Queryable index of one parsed module."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        ix = _Indexer()
        # two passes so alias calls textually before/after the alias def both
        # resolve (class bodies execute out of line anyway)
        ix.visit(tree)
        if ix.jit_aliases:
            ix2 = _Indexer()
            ix2.jit_aliases = ix.jit_aliases
            ix2.visit(tree)
            ix = ix2
        self.functions = ix.functions
        self.jit_aliases = ix.jit_aliases
        self._raw_jit_calls = ix.raw_jit_calls
        self.jit_sites = [self._make_site(n, q) for n, q in ix.raw_jit_calls]
        self.jit_sites += [self._make_decorator_site(d, f, q)
                           for d, f, q in ix.raw_jit_decorators]
        self.jitted_fn_nodes = self._collect_jitted()

    # -- function resolution -------------------------------------------------
    def resolve_function(self, name: str, scope: str) -> Optional[FunctionInfo]:
        """Look `name` up as a nested def of `scope` (walking outward), a
        method of the enclosing class, then a module-level function."""
        parts = scope.split(".") if scope else []
        for i in range(len(parts), -1, -1):
            qn = ".".join(parts[:i] + [name])
            if qn in self.functions:
                return self.functions[qn]
        return None

    def _resolve_callable(self, node: ast.AST, scope: str
                          ) -> Tuple[str, Optional[FunctionInfo], Optional[ast.AST]]:
        """(display name, FunctionInfo|None, node|None) for a jit argument."""
        if isinstance(node, ast.Lambda):
            info = FunctionInfo("<lambda>", node, _params_of(node), scope)
            return "<lambda>", info, node
        d = dotted_name(node)
        if d is not None and "." not in d:
            info = self.resolve_function(d, scope)
            return d, info, info.node if info else None
        if isinstance(node, ast.Call):
            fd = dotted_name(node.func) or ""
            if fd.split(".")[-1] == "partial" and node.args:
                # functools.partial(f, ...) -> resolve f; partial-bound
                # leading args are dropped from the effective signature
                name, info, fnode = self._resolve_callable(node.args[0], scope)
                if info is not None:
                    bound = len(node.args) - 1
                    kw = {k.arg for k in node.keywords if k.arg}
                    params = [p for p in info.params[bound:] if p not in kw]
                    info = FunctionInfo(info.qualname, info.node, params,
                                        info.scope)
                return f"partial({name})", info, fnode
        return d or "<expr>", None, None

    def _make_site(self, node: ast.Call, qualname: str) -> JitSite:
        d = dotted_name(node.func) or ""
        kind = "shard_map" if _SHARD_RE.search(d) else "jit"
        fn_name, info, fn_node = ("<none>", None, None)
        if node.args:
            fn_name, info, fn_node = self._resolve_callable(node.args[0],
                                                            qualname)
        donate: Optional[bool] = None
        if kind == "jit":
            donate = any(k.arg in ("donate_argnums", "donate_argnames")
                         for k in node.keywords)
            if not donate and d in self.jit_aliases and len(node.args) >= 2:
                donate = True       # alias signature: (fn, donate_argnums, ...)
            elif not donate and d not in self.jit_aliases:
                donate = False
        return JitSite(node, kind, qualname, fn_name,
                       info.params if info else None, fn_node, donate)

    def _make_decorator_site(self, dec: ast.AST, fn_node: ast.AST,
                             qualname: str) -> JitSite:
        """@jax.jit-style decoration: the decorated def IS the jitted fn; the
        site registers under the function's own qualname."""
        donate = False
        if isinstance(dec, ast.Call):
            donate = any(k.arg in ("donate_argnums", "donate_argnames")
                         for k in dec.keywords)
        return JitSite(dec, "jit", qualname, fn_node.name,
                       _params_of(fn_node), fn_node, donate)

    def _collect_jitted(self) -> List[Tuple[ast.AST, List[str], str]]:
        """(fn node, data params, display name) for every function that gets
        traced: jit/shard_map arguments plus @jit-style decorators."""
        out = []
        seen = set()
        for site in self.jit_sites:
            if site.fn_node is not None and id(site.fn_node) not in seen:
                seen.add(id(site.fn_node))
                out.append((site.fn_node, site.fn_params or [],
                            f"{site.qualname or '<module>'}::{site.fn_name}"))
        for info in self.functions.values():
            node = info.node
            for dec in getattr(node, "decorator_list", []):
                dd = dotted_name(dec) or ""
                if isinstance(dec, ast.Call):
                    dd = dotted_name(dec.func) or ""
                    if dd.split(".")[-1] == "partial" and dec.args:
                        dd = dotted_name(dec.args[0]) or ""
                if dd in _JIT_FUNCS and id(node) not in seen:
                    seen.add(id(node))
                    out.append((node, info.params, info.qualname))
        return out

    # -- hot-path reachability ----------------------------------------------
    def hot_functions(self, roots: Iterable[str] = HOT_ROOTS
                      ) -> List[FunctionInfo]:
        """Functions reachable (same-file call graph) from any function whose
        bare name is in `roots`.  Edges: `self.m()` / `cls.m()` -> any method
        `m` in this module; bare `f()` -> nested def or module function."""
        by_bare: Dict[str, List[FunctionInfo]] = {}
        for info in self.functions.values():
            by_bare.setdefault(info.qualname.split(".")[-1], []).append(info)
        work = [f for r in roots for f in by_bare.get(r, [])]
        reached: Dict[str, FunctionInfo] = {f.qualname: f for f in work}
        while work:
            fn = work.pop()
            for call in fn.calls:
                parts = call.split(".")
                if len(parts) == 2 and parts[0] in ("self", "cls"):
                    cands = by_bare.get(parts[1], [])
                elif len(parts) == 1:
                    target = self.resolve_function(parts[0], fn.qualname)
                    cands = [target] if target else []
                else:
                    cands = []
                for c in cands:
                    if c.qualname not in reached:
                        reached[c.qualname] = c
                        work.append(c)
        return list(reached.values())


# ---------------------------------------------------------------------------
# device-value taint over hot functions
# ---------------------------------------------------------------------------


class _TaintPass:
    """Forward pass over a hot function's statements: track names bound to
    device dispatch results; emit sync events when they are scalarized,
    bool()-ed, or bulk-fetched (with span context)."""

    def __init__(self, finfo: FunctionInfo):
        self.finfo = finfo
        self.tainted: Set[str] = set()
        self.events: List[SyncEvent] = []

    # -- expression queries --------------------------------------------------
    def _is_device_call(self, call: ast.Call) -> bool:
        d = dotted_name(call.func)
        return bool(d and _DEVICE_CALL_RE.search(d)
                    and d not in _FETCH_FUNCS
                    and not _SPAN_CALL_RE.search(d))

    def _expr_tainted(self, node: ast.AST) -> bool:
        """Whether `node` evaluates to (or through) a device value.  Fetch and
        scalarize calls are opaque: `int(np.asarray(x)[0])` is ONE sync (the
        asarray), and its result is host data — looking through them would
        double-count every laundered value."""
        if isinstance(node, (ast.Name, ast.Attribute)):
            d = dotted_name(node)
            if d in self.tainted:
                return True
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d in _FETCH_FUNCS or d in _SCALARIZE_FUNCS or \
                    (isinstance(node.func, ast.Attribute) and
                     node.func.attr == "item"):
                return False            # sync boundary: result is host data
            if self._is_device_call(node):
                return True
        return any(self._expr_tainted(c) for c in ast.iter_child_nodes(node))

    def _sync_kind(self, call: ast.Call) -> Optional[Tuple[str, str]]:
        """(kind, label) when `call` is a sync op on a tainted value."""
        d = dotted_name(call.func)
        if d in _FETCH_FUNCS and call.args and \
                self._expr_tainted(call.args[0]):
            return "fetch", f"{d}(...)"
        if d in _SCALARIZE_FUNCS and call.args and \
                self._expr_tainted(call.args[0]):
            return "scalarize", f"{d}(...)"
        if isinstance(call.func, ast.Attribute) and call.func.attr == "item" \
                and self._expr_tainted(call.func.value):
            return "scalarize", ".item()"
        return None

    def _scan_expr(self, node: ast.AST, span: int) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                sk = self._sync_kind(sub)
                if sk is not None:
                    kind, what = sk
                    if kind == "fetch" and span > 0:
                        continue        # timed fetch: exactly what we want
                    self.events.append(SyncEvent(sub, kind, what,
                                                 self.finfo.qualname,
                                                 span > 0))

    # -- statement walk ------------------------------------------------------
    def _assign(self, targets: Sequence[ast.AST], value: ast.AST) -> None:
        rhs_tainted = self._expr_tainted(value)
        if isinstance(value, ast.Call) and self._sync_kind(value) is not None:
            rhs_tainted = False         # the sync resolved it to host data
        names: List[str] = []
        for t in targets:
            if isinstance(t, ast.Tuple):
                names += [dotted_name(e) for e in t.elts]
            else:
                names.append(dotted_name(t))
        for n in names:
            if n is None:
                continue
            if rhs_tainted:
                self.tainted.add(n)
            else:
                self.tainted.discard(n)

    def _is_span_with(self, item: ast.withitem) -> bool:
        if isinstance(item.context_expr, ast.Call):
            d = dotted_name(item.context_expr.func)
            return bool(d and _SPAN_CALL_RE.search(d))
        return False

    def walk(self, body: Sequence[ast.stmt], span: int = 0) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.Assign,)):
                self._scan_expr(stmt.value, span)
                self._assign(stmt.targets, stmt.value)
            elif isinstance(stmt, ast.AugAssign):
                self._scan_expr(stmt.value, span)
                if self._expr_tainted(stmt.value):
                    n = dotted_name(stmt.target)
                    if n:
                        self.tainted.add(n)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._scan_expr(stmt.value, span)
                self._assign([stmt.target], stmt.value)
            elif isinstance(stmt, ast.Expr):
                self._scan_expr(stmt.value, span)
            elif isinstance(stmt, (ast.If, ast.While)):
                self._scan_expr(stmt.test, span)
                if self._expr_tainted(stmt.test) and not (
                        isinstance(stmt.test, ast.Call) and
                        self._sync_kind(stmt.test)):
                    self.events.append(SyncEvent(
                        stmt.test, "implicit_bool", "if/while test",
                        self.finfo.qualname, span > 0))
                self.walk(stmt.body, span)
                self.walk(stmt.orelse, span)
            elif isinstance(stmt, ast.For):
                self._scan_expr(stmt.iter, span)
                if self._expr_tainted(stmt.iter):
                    self._assign([stmt.target], stmt.iter)
                self.walk(stmt.body, span)
                self.walk(stmt.orelse, span)
            elif isinstance(stmt, ast.With):
                entered = span + (1 if any(self._is_span_with(i)
                                           for i in stmt.items) else 0)
                for i in stmt.items:
                    if not self._is_span_with(i):
                        self._scan_expr(i.context_expr, span)
                self.walk(stmt.body, entered)
            elif isinstance(stmt, ast.Try):
                self.walk(stmt.body, span)
                for h in stmt.handlers:
                    self.walk(h.body, span)
                self.walk(stmt.orelse, span)
                self.walk(stmt.finalbody, span)
            elif isinstance(stmt, (ast.Return, ast.Raise)) and \
                    getattr(stmt, "value", None) is not None:
                self._scan_expr(stmt.value, span)
            # nested defs are separate functions; the call graph carries them


def _hot_sync_events(index: ModuleIndex) -> List[SyncEvent]:
    events: List[SyncEvent] = []
    for finfo in index.hot_functions():
        if not isinstance(finfo.node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
            continue
        tp = _TaintPass(finfo)
        tp.walk(finfo.node.body)
        events.extend(e for e in tp.events if not e.spanned)
    return events


# ---------------------------------------------------------------------------
# traced-branch detection (TPL004)
# ---------------------------------------------------------------------------


def _traced_branches(index: ModuleIndex) -> List[TracedBranch]:
    out = []
    for fn_node, params, display in index.jitted_fn_nodes:
        data = [p for p in params if p not in _STATIC_PARAM_NAMES]
        if not data or isinstance(fn_node, ast.Lambda):
            continue
        for stmt in ast.walk(fn_node):
            if not isinstance(stmt, (ast.If, ast.While)):
                continue
            param = _branch_on_param(stmt.test, set(data))
            if param is not None:
                out.append(TracedBranch(
                    stmt, "if" if isinstance(stmt, ast.If) else "while",
                    param, display))
    return out


def _branch_on_param(test: ast.AST, data: Set[str]) -> Optional[str]:
    """The offending parameter name when `test` branches on a traced value;
    None when every reference is statically safe (shape/dtype access,
    `is None`, len/isinstance)."""
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(test):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name) and node.id in data):
            continue
        p = parents.get(id(node))
        safe = False
        hops = 0
        cur, prev = p, node
        while cur is not None and hops < 6:
            if isinstance(cur, ast.Attribute) and cur.value is prev:
                safe = True             # x.shape / x.dtype / x.ndim — static
                break
            if isinstance(cur, ast.Compare) and \
                    all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in cur.ops):
                safe = True             # x is None
                break
            if isinstance(cur, ast.Call):
                fd = dotted_name(cur.func) or ""
                if fd.split(".")[-1] in ("len", "isinstance", "getattr",
                                         "hasattr", "callable"):
                    safe = True         # static under tracing
                    break
            prev, cur = cur, parents.get(id(cur))
            hops += 1
        if not safe:
            return node.id
    return None


# ---------------------------------------------------------------------------
# double-buffer hazards (TPL007)
# ---------------------------------------------------------------------------

# page-state mutators: calls that free/reassign KV pages or stores into the
# per-slot length/table/refcount arrays.  A public entry point of a
# double-buffered engine must harvest the in-flight batch before any of
# these run, or the in-flight dispatch's KV writes land in pages the host
# has already handed to someone else (the invariant `abort()` relies on).
_PAGE_MUTATOR_ATTRS = frozenset({"release", "allocate", "allocate_prefixed"})
_PAGE_STATE_ATTRS = frozenset({"lengths", "page_table", "refcounts",
                               "ref_counts"})


def _publishes_inflight(info: FunctionInfo) -> bool:
    """Whether this function assigns a non-None value to `self._inflight` —
    the double-buffering marker (fuse=True paths park the un-synced dispatch
    there; `None` assignments are the harvest clearing it)."""
    for node in ast.walk(info.node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if dotted_name(t) == "self._inflight" and not (
                        isinstance(node.value, ast.Constant) and
                        node.value.value is None):
                    return True
    return False


def _direct_mutations(info: FunctionInfo) -> List[Tuple[ast.AST, str]]:
    """(node, description) for every direct page-state mutation in `info`."""
    out: List[Tuple[ast.AST, str]] = []
    for node in ast.walk(info.node):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _PAGE_MUTATOR_ATTRS:
            out.append((node, f".{node.func.attr}()"))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Attribute) and \
                        t.value.attr in _PAGE_STATE_ATTRS:
                    out.append((node, f".{t.value.attr}[...] store"))
    return out


def _direct_harvests(info: FunctionInfo) -> List[ast.AST]:
    return [node for node in ast.walk(info.node)
            if isinstance(node, ast.Call) and
            (dotted_name(node.func) or "").split(".")[-1] == "_harvest"]


def _double_buffer_hazards(index: ModuleIndex) -> List[DoubleBufferHazard]:
    """Public methods of a double-buffered class that (transitively, same
    file) mutate page-table/refcount state BEFORE any harvest of the
    in-flight batch.  Position is compared by line number: the mutation's
    position is its own line for a direct write, or the call site's line
    when it happens inside a callee — so `step()`'s harvest-at-the-top
    pattern and `abort()`'s harvest-guard both pass, and a tie (one call
    that both harvests and mutates, like `run()` -> `step()`) passes too."""
    classes = {info.scope for info in index.functions.values()
               if info.scope and _publishes_inflight(info)}
    if not classes:
        return []
    hazards: List[DoubleBufferHazard] = []
    for cls in classes:
        methods = {i.qualname.split(".")[-1]: i
                   for i in index.functions.values() if i.scope == cls}

        def closure(name: str) -> Set[str]:
            seen: Set[str] = set()
            work = [name]
            while work:
                cur = work.pop()
                info = methods.get(cur)
                if info is None or cur in seen:
                    continue
                seen.add(cur)
                for call in info.calls:
                    parts = call.split(".")
                    if len(parts) == 2 and parts[0] in ("self", "cls") and \
                            parts[1] in methods:
                        work.append(parts[1])
            return seen

        mutates = {name: bool(_direct_mutations(i))
                   for name, i in methods.items()}
        harvests = {name: bool(_direct_harvests(i))
                    for name, i in methods.items()}
        for name, info in methods.items():
            if name.startswith("_") or not isinstance(
                    info.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            first_mut: Optional[Tuple[int, ast.AST, str]] = None
            first_harv: Optional[int] = None
            for node, what in _direct_mutations(info):
                ln = getattr(node, "lineno", 1)
                if first_mut is None or ln < first_mut[0]:
                    first_mut = (ln, node, what)
            for node in _direct_harvests(info):
                ln = getattr(node, "lineno", 1)
                if first_harv is None or ln < first_harv:
                    first_harv = ln
            # call sites into mutating / harvesting callees
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                parts = (d or "").split(".")
                if len(parts) == 2 and parts[0] in ("self", "cls"):
                    callee = parts[1]
                    ln = getattr(node, "lineno", 1)
                    sub = closure(callee)
                    if any(mutates.get(m) for m in sub):
                        if first_mut is None or ln < first_mut[0]:
                            first_mut = (ln, node, f"via self.{callee}()")
                    if any(m == "_harvest" or harvests.get(m) for m in sub):
                        if first_harv is None or ln < first_harv:
                            first_harv = ln
            if first_mut is not None and (first_harv is None or
                                          first_harv > first_mut[0]):
                hazards.append(DoubleBufferHazard(
                    first_mut[1], info.qualname, first_mut[2]))
    return hazards


# ---------------------------------------------------------------------------
# broad except handlers around device code (TPL006)
# ---------------------------------------------------------------------------


# TPL006 uses a stricter device pattern than the taint pass: `*_fn` names in
# try bodies are usually user callbacks (collate_fn, init_fn), not dispatches
_TRY_DEVICE_RE = re.compile(r"^(jax|jnp)\.|(^|\.)pallas_call$")


def _broad_device_handlers(tree: ast.Module) -> List[BroadHandler]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        device_calls: Set[str] = set()
        for sub in ast.walk(ast.Module(body=node.body, type_ignores=[])):
            if isinstance(sub, ast.Call):
                d = dotted_name(sub.func)
                if d and _TRY_DEVICE_RE.search(d):
                    device_calls.add(d)
        if not device_calls:
            continue
        for h in node.handlers:
            caught = None
            if h.type is None:
                caught = "<bare>"
            else:
                types = h.type.elts if isinstance(h.type, ast.Tuple) \
                    else [h.type]
                if any((dotted_name(t) or "").split(".")[-1] in
                       ("Exception", "BaseException") for t in types):
                    caught = dotted_name(h.type) if not isinstance(
                        h.type, ast.Tuple) else "Exception"
            if caught:
                out.append(BroadHandler(h, caught, device_calls))
    return out


# ---------------------------------------------------------------------------
# file context + driver
# ---------------------------------------------------------------------------


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _relpath(path: str) -> str:
    """Repo-relative '/'-separated path (registry key form); paths outside
    the repo stay as given."""
    rel = os.path.relpath(os.path.abspath(path), repo_root())
    return path if rel.startswith("..") else rel.replace(os.sep, "/")


class FileContext:
    """Everything the rules need about one file, built once."""

    def __init__(self, path: str, source: str, registry) -> None:
        self.path = path
        self.relpath = _relpath(path)
        self.source = source
        self.registry = registry
        self.suppressions = Suppressions(source)
        tree = ast.parse(source, filename=path)
        self.index = ModuleIndex(tree)
        self.jit_sites = self.index.jit_sites
        self.hot_sync_events = _hot_sync_events(self.index)
        self.traced_branches = _traced_branches(self.index)
        self.broad_device_handlers = _broad_device_handlers(tree)
        self.db_hazards = _double_buffer_hazards(self.index)


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def run_ast_checks(paths: Sequence[str], rules=None,
                   registry=None) -> List[Finding]:
    """Level 1: run every AST rule over the python files under `paths`.
    Returns ALL findings; suppressed ones carry suppressed=True.  `registry`
    defaults to `analysis.registry` (injectable for fixture tests)."""
    if registry is None:
        from . import registry as registry_mod
        registry = registry_mod
    rules = list(rules) if rules is not None else list(AST_RULES)
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            ctx = FileContext(path, source, registry)
        except SyntaxError as e:
            findings.append(Finding("LINT001", path, e.lineno or 1, 0,
                                    f"syntax error: {e.msg}"))
            continue
        file_findings: List[Finding] = []
        for rule in rules:
            file_findings.extend(rule.check(ctx))
        findings.extend(ctx.suppressions.apply(file_findings))
    # orphaned registry entries: a declared program source whose FILE is gone
    # (deleted/renamed) never gets a FileContext, so the per-file stale check
    # above cannot see it — sweep every entry under the linted directories
    linted = {_relpath(p) for p in iter_python_files(paths)}
    # absolute-path containment, not relpath string prefixes: roots spelled
    # as '.', 'paddle_tpu/', or an ancestor must all cover the same entries
    dir_roots = [os.path.abspath(p) for p in paths if os.path.isdir(p)]
    for entry in getattr(registry, "PROGRAM_SOURCES", ()):
        if entry.path in linted:
            continue
        entry_abs = os.path.abspath(
            entry.path if os.path.isabs(entry.path)
            else os.path.join(repo_root(), entry.path))
        if any(entry_abs.startswith(root + os.sep) for root in dir_roots):
            findings.append(Finding(
                "TPL002", entry.path, 1, 0,
                f"registry entry `{entry.qualname or '<module>'}` declares a "
                f"program source in a file that no longer exists — remove it "
                f"from analysis/registry.py"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
