"""Optimizers (reference: `python/paddle/optimizer/` — SGD, Momentum, Adam, AdamW,
Adamax, Adagrad, Adadelta, RMSProp, Lamb, LBFGS; fused `_C_ops.adam_` parity is one
jnp-fused update per parameter)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import lr  # noqa
from .optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _append_optimize_op(self, p, g):
        lr = self._lr_for(p)
        p._data = (p._data.astype(jnp.float32) - lr * g._data.astype(jnp.float32)) \
            .astype(p._data.dtype)

    def _functional_update(self, param, grad, state, lr):
        return param - lr * grad, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _acc_names(self):
        return ["velocity"]

    def _append_optimize_op(self, p, g):
        lr = self._lr_for(p)
        v = self._acc("velocity", p)
        g32 = g._data.astype(jnp.float32)
        v = self._momentum * v + g32
        if self._use_nesterov:
            upd = g32 + self._momentum * v
        else:
            upd = v
        self._set_acc("velocity", p, v)
        p._data = (p._data.astype(jnp.float32) - lr * upd).astype(p._data.dtype)

    def _init_functional_state(self, param):
        return {"velocity": jnp.zeros_like(param, dtype=jnp.float32)}

    def _functional_update(self, param, grad, state, lr):
        v = self._momentum * state["velocity"] + grad
        upd = grad + self._momentum * v if self._use_nesterov else v
        return param - lr * upd, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, name=None,
                 amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._multi_precision = multi_precision

    def _acc_names(self):
        return ["moment1", "moment2", "beta1_pow", "beta2_pow"]

    def _beta(self, b):
        return float(b.item()) if isinstance(b, Tensor) else float(b)

    def _append_optimize_op(self, p, g):
        lr = self._lr_for(p)
        b1 = self._beta(self._beta1)
        b2 = self._beta(self._beta2)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p, jnp.asarray(1.0, jnp.float32))
        b2p = self._acc("beta2_pow", p, jnp.asarray(1.0, jnp.float32))
        g32 = g._data.astype(jnp.float32)
        m = b1 * m + (1 - b1) * g32
        v = b2 * v + (1 - b2) * g32 * g32
        b1p = b1p * b1
        b2p = b2p * b2
        mhat = m / (1 - b1p)
        vhat = v / (1 - b2p)
        upd = mhat / (jnp.sqrt(vhat) + self._epsilon)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        self._set_acc("beta1_pow", p, b1p)
        self._set_acc("beta2_pow", p, b2p)
        p._data = (p._data.astype(jnp.float32) - lr * upd).astype(p._data.dtype)

    def _init_functional_state(self, param):
        return {"m": jnp.zeros_like(param, dtype=jnp.float32),
                "v": jnp.zeros_like(param, dtype=jnp.float32),
                "b1p": jnp.ones((), jnp.float32),
                "b2p": jnp.ones((), jnp.float32)}

    def _functional_update(self, param, grad, state, lr):
        b1 = self._beta(self._beta1)
        b2 = self._beta(self._beta2)
        g32 = grad.astype(jnp.float32)
        m = b1 * state["m"] + (1 - b1) * g32
        v = b2 * state["v"] + (1 - b2) * g32 * g32
        b1p = state["b1p"] * b1
        b2p = state["b2p"] * b2
        upd = (m / (1 - b1p)) / (jnp.sqrt(v / (1 - b2p)) + self._epsilon)
        new_p = (param.astype(jnp.float32) - lr * upd).astype(param.dtype)
        return new_p, {"m": m, "v": v, "b1p": b1p, "b2p": b2p}


class AdamW(Adam):
    """Decoupled weight decay (reference `optimizer/adamw.py`)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None, amsgrad=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None,
                         grad_clip, lazy_mode, multi_precision, name=name)
        if isinstance(weight_decay, Tensor):
            self._coeff = float(weight_decay.item())
        else:
            self._coeff = float(weight_decay)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _append_optimize_op(self, p, g):
        lr = self._lr_for(p)
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        if self._apply_decay_param_fun is None or self._apply_decay_param_fun(p.name):
            p._data = (p._data.astype(jnp.float32) * (1.0 - lr * self._coeff)) \
                .astype(p._data.dtype)
        super()._append_optimize_op(p, g)

    def _functional_update(self, param, grad, state, lr):
        decayed = param.astype(jnp.float32) * (1.0 - lr * self._coeff)
        return super()._functional_update(decayed.astype(param.dtype), grad, state, lr)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _acc_names(self):
        return ["moment", "inf_norm", "beta1_pow"]

    def _append_optimize_op(self, p, g):
        lr = self._lr_for(p)
        m = self._acc("moment", p)
        u = self._acc("inf_norm", p)
        b1p = self._acc("beta1_pow", p, jnp.asarray(1.0, jnp.float32))
        g32 = g._data.astype(jnp.float32)
        m = self._beta1 * m + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * u, jnp.abs(g32) + self._epsilon)
        b1p = b1p * self._beta1
        self._set_acc("moment", p, m)
        self._set_acc("inf_norm", p, u)
        self._set_acc("beta1_pow", p, b1p)
        p._data = (p._data.astype(jnp.float32) - lr / (1 - b1p) * (m / u)) \
            .astype(p._data.dtype)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc_value = initial_accumulator_value

    def _acc_names(self):
        return ["moment"]

    def _append_optimize_op(self, p, g):
        lr = self._lr_for(p)
        acc = self._acc("moment", p, jnp.full(p._data.shape, self._init_acc_value,
                                              jnp.float32))
        g32 = g._data.astype(jnp.float32)
        acc = acc + g32 * g32
        self._set_acc("moment", p, acc)
        p._data = (p._data.astype(jnp.float32)
                   - lr * g32 / (jnp.sqrt(acc) + self._epsilon)).astype(p._data.dtype)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _acc_names(self):
        return ["avg_squared_grad", "avg_squared_update"]

    def _append_optimize_op(self, p, g):
        lr = self._lr_for(p)
        Eg = self._acc("avg_squared_grad", p)
        Ex = self._acc("avg_squared_update", p)
        g32 = g._data.astype(jnp.float32)
        Eg = self._rho * Eg + (1 - self._rho) * g32 * g32
        upd = jnp.sqrt(Ex + self._epsilon) / jnp.sqrt(Eg + self._epsilon) * g32
        Ex = self._rho * Ex + (1 - self._rho) * upd * upd
        self._set_acc("avg_squared_grad", p, Eg)
        self._set_acc("avg_squared_update", p, Ex)
        p._data = (p._data.astype(jnp.float32) - lr * upd).astype(p._data.dtype)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _acc_names(self):
        return ["mean_square", "mean_grad", "momentum"]

    def _append_optimize_op(self, p, g):
        lr = self._lr_for(p)
        ms = self._acc("mean_square", p)
        mom = self._acc("momentum", p)
        g32 = g._data.astype(jnp.float32)
        ms = self._rho * ms + (1 - self._rho) * g32 * g32
        if self._centered:
            mg = self._acc("mean_grad", p)
            mg = self._rho * mg + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
            self._set_acc("mean_grad", p, mg)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * mom + lr * g32 / denom
        self._set_acc("mean_square", p, ms)
        self._set_acc("momentum", p, mom)
        p._data = (p._data.astype(jnp.float32) - mom).astype(p._data.dtype)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _acc_names(self):
        return ["moment1", "moment2", "beta1_pow", "beta2_pow"]

    def _append_optimize_op(self, p, g):
        lr = self._lr_for(p)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p, jnp.asarray(1.0, jnp.float32))
        b2p = self._acc("beta2_pow", p, jnp.asarray(1.0, jnp.float32))
        g32 = g._data.astype(jnp.float32)
        m = self._beta1 * m + (1 - self._beta1) * g32
        v = self._beta2 * v + (1 - self._beta2) * g32 * g32
        b1p = b1p * self._beta1
        b2p = b2p * self._beta2
        mhat = m / (1 - b1p)
        vhat = v / (1 - b2p)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        wd = 0.0 if (self._exclude_fn is not None and self._exclude_fn(p)) else self._wd
        p32 = p._data.astype(jnp.float32)
        r = r + wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        self._set_acc("moment1", p, m)
        self._set_acc("moment2", p, v)
        self._set_acc("beta1_pow", p, b1p)
        self._set_acc("beta2_pow", p, b2p)
        p._data = (p32 - lr * trust * r).astype(p._data.dtype)


class LBFGS(Optimizer):
    """L-BFGS (reference `optimizer/lbfgs.py`): closure-based full-batch optimizer."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-07, tolerance_change=1e-09, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._max_iter = max_iter
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history = history_size
        self._s, self._y = [], []
        self._prev_flat_grad = None
        self._prev_step = None  # displacement applied at the previous call

    def _gather(self):
        return jnp.concatenate([p.grad._data.astype(jnp.float32).reshape(-1)
                                for p in self._parameter_list])

    def _distribute(self, flat):
        off = 0
        for p in self._parameter_list:
            n = p._data.size
            p._data = (p._data.astype(jnp.float32)
                       + flat[off:off + n].reshape(p._data.shape)).astype(p._data.dtype)
            off += n

    def step(self, closure=None):
        if closure is None:
            # fall back to a plain gradient step
            g = self._gather()
            self._distribute(-self.get_lr() * g)
            return None
        loss = closure()
        g = self._gather()
        # curvature pair from the PREVIOUS step: s = x_k - x_{k-1}, y = g_k - g_{k-1}
        if self._prev_flat_grad is not None and self._prev_step is not None:
            y_new = g - self._prev_flat_grad
            s_new = self._prev_step
            # tpu-lint: disable=TPL001 -- L-BFGS curvature acceptance is inherently a host decision (python-list history); one scalar sync per step
            if float(jnp.dot(y_new, s_new)) > 1e-10:  # keep B positive-definite
                self._s.append(s_new)
                self._y.append(y_new)
                if len(self._s) > self._history:
                    self._s.pop(0)
                    self._y.pop(0)
        # two-loop recursion
        q = g
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / jnp.maximum(jnp.dot(y, s), 1e-10)
            a = rho * jnp.dot(s, q)
            q = q - a * y
            alphas.append((rho, a))
        if self._y:
            y_last, s_last = self._y[-1], self._s[-1]
            gamma = jnp.dot(s_last, y_last) / jnp.maximum(jnp.dot(y_last, y_last), 1e-10)
            q = gamma * q
        for (rho, a), s, y in zip(reversed(alphas), self._s, self._y):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        step_dir = -q
        lr = self.get_lr()
        self._distribute(lr * step_dir)
        self._prev_step = lr * step_dir
        self._prev_flat_grad = g
        return loss


__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
           "Adadelta", "RMSProp", "Lamb", "LBFGS", "lr"]
