"""Optimizer base (reference: `python/paddle/optimizer/optimizer.py` — `step` :1583,
`_apply_optimize` :1278).

Eager path: per-parameter fused update lambdas over jnp arrays (the reference calls fused
phi kernels like `_C_ops.adam_`); accumulators live in `_accumulators[name][param.name]`.
The jit/`to_static` train-step path re-expresses the same math functionally via
`_functional_update`, so one optimizer implementation serves both.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = self._flatten_params(parameters)
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay
        self._accumulators: Dict[str, Dict[int, jnp.ndarray]] = defaultdict(dict)
        self._global_step = 0
        self._name = name

    @staticmethod
    def _flatten_params(parameters):
        if parameters is None:
            return None
        out = []
        for p in parameters:
            if isinstance(p, dict):  # param group
                out.extend(p["params"])
            else:
                out.append(p)
        return out

    # ---- lr ----
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    def _lr_for(self, p) -> float:
        base = self.get_lr()
        return base * p._optimize_attrs.get("learning_rate", 1.0) \
            if hasattr(p, "_optimize_attrs") else base

    # ---- accumulators ----
    def _acc(self, name, p, init=None):
        store = self._accumulators[name]
        key = id(p)
        if key not in store:
            store[key] = jnp.zeros_like(p._data, dtype=jnp.float32) if init is None \
                else init
        return store[key]

    def _set_acc(self, name, p, value):
        self._accumulators[name][id(p)] = value

    # ---- main API ----
    @no_grad()
    def step(self):
        params_grads = []
        for p in self._parameter_list or []:
            if p.stop_gradient or p.grad is None:
                continue
            params_grads.append((p, p.grad))
        self._apply_optimize(params_grads)

    def _apply_optimize(self, params_grads):
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        params_grads = self._apply_decay(params_grads)
        self._global_step += 1
        for p, g in params_grads:
            if g is None:
                continue
            self._append_optimize_op(p, g)

    def _apply_decay(self, params_grads):
        """Coupled L2 regularization (reference regularizer path): grad += coeff * p."""
        wd = self._weight_decay
        if wd is None or isinstance(wd, float) and wd == 0.0:
            return params_grads
        if not isinstance(wd, float):
            from ..regularizer import L2Decay
            if isinstance(wd, L2Decay):
                wd = wd._coeff
            else:
                return params_grads  # L1 etc. handled by regularizer directly
        out = []
        for p, g in params_grads:
            reg = p._optimize_attrs.get("regularizer") if hasattr(p, "_optimize_attrs") else None
            if reg is not None or g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(g._data + wd * p._data.astype(g._data.dtype),
                                  stop_gradient=True)))
        return out

    def _append_optimize_op(self, p, g):
        raise NotImplementedError

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list or []:
            p.clear_grad(set_to_zero=set_to_zero and p.grad is not None)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..core.tensor import _static_recorder
        if _static_recorder[0] is not None:
            # static mode (ref static minimize appends backward + update ops to
            # the Program): record the train step as a program op executed by
            # Executor.run, instead of running it at build time
            opt = self

            def train_op():
                loss.backward(retain_graph=True)
                opt.step()
                opt.clear_grad()
            _static_recorder[0]._record_py(train_op)
            return None, None
        # skip backward when an explicit loss.backward() already ran (directly
        # tracked, so retain_graph=True doesn't double-accumulate grads) —
        # reference minimize only collects existing grads in that pattern
        node = getattr(loss, "_grad_node", None)
        if node is not None and node.vjp_fn is not None \
                and not getattr(loss, "_backward_ran", False):
            loss.backward()
        self.step()
        return None, None

    # ---- state ----
    def state_dict(self):
        state = {}
        for name, store in self._accumulators.items():
            for key, val in store.items():
                pname = self._param_name(key)
                state[f"{pname}_{name}"] = Tensor(val, stop_gradient=True)
        if isinstance(self._learning_rate, LRScheduler):
            state["LR_Scheduler"] = self._learning_rate.state_dict()
        state["global_step"] = self._global_step
        return state

    def _param_name(self, key):
        for p in self._parameter_list or []:
            if id(p) == key:
                return p.name
        return str(key)

    def set_state_dict(self, state_dict):
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        self._global_step = int(state_dict.get("global_step", 0))
        for p in self._parameter_list or []:
            for name in list(self._accumulators.keys()) + list(self._acc_names()):
                k = f"{p.name}_{name}"
                if k in state_dict:
                    v = state_dict[k]
                    self._accumulators[name][id(p)] = (
                        v._data if isinstance(v, Tensor) else jnp.asarray(v))

    def _acc_names(self):
        return []

    # ---- functional form (used by to_static / jit train steps) ----
    def _functional_update(self, param, grad, state, lr):
        """Pure update: (param, grad, state dict, lr) -> (new_param, new_state)."""
        raise NotImplementedError(f"{type(self).__name__} has no functional form")

    def _init_functional_state(self, param):
        return {}
