"""Probability distributions (reference: `python/paddle/distribution/` — 15+
distributions + transforms + kl).  Built on jax.random + jax.scipy."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import generator as _gen
from ..core.tensor import Tensor, apply, _to_data


def _t(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x, jnp.float32)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(_t(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(self._batch_shape)
        z = jax.random.normal(_gen.next_key(), shape)
        return Tensor(self.loc + self.scale * z)

    def log_prob(self, value):
        v = _t(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale)
                      - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
                      + jnp.zeros(self._batch_shape))

    def cdf(self, value):
        return Tensor(0.5 * (1 + jax.scipy.special.erf(
            (_t(value) - self.loc) / (self.scale * math.sqrt(2)))))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(np.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(self._batch_shape)
        u = jax.random.uniform(_gen.next_key(), shape)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _t(value)
        inside = (v >= self.low) & (v < self.high)
        return Tensor(jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _t(logits)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=()):
        out = jax.random.categorical(_gen.next_key(), self.logits,
                                     shape=tuple(shape) + tuple(self._batch_shape))
        return Tensor(out.astype(jnp.int64))

    def log_prob(self, value):
        lp = jax.nn.log_softmax(self.logits, axis=-1)
        v = _t(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(lp, v[..., None], axis=-1)[..., 0])

    def probs(self, value=None):
        p = jax.nn.softmax(self.logits, axis=-1)
        if value is None:
            return Tensor(p)
        v = _t(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(p, v[..., None], axis=-1)[..., 0])

    def entropy(self):
        lp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(-jnp.sum(jnp.exp(lp) * lp, axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_v = _t(probs)
        super().__init__(self.probs_v.shape)

    def sample(self, shape=()):
        u = jax.random.uniform(_gen.next_key(),
                               tuple(shape) + tuple(self._batch_shape))
        return Tensor((u < self.probs_v).astype(jnp.float32))

    def log_prob(self, value):
        v = _t(value)
        p = jnp.clip(self.probs_v, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs_v, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(np.broadcast_shapes(self.alpha.shape, self.beta.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        return Tensor(jax.random.beta(_gen.next_key(), self.alpha, self.beta, shape))

    def log_prob(self, value):
        v = _t(value)
        lbeta = (jax.scipy.special.gammaln(self.alpha)
                 + jax.scipy.special.gammaln(self.beta)
                 - jax.scipy.special.gammaln(self.alpha + self.beta))
        return Tensor((self.alpha - 1) * jnp.log(v) + (self.beta - 1) * jnp.log1p(-v)
                      - lbeta)


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(np.broadcast_shapes(self.concentration.shape, self.rate.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        g = jax.random.gamma(_gen.next_key(), self.concentration, shape)
        return Tensor(g / self.rate)

    def log_prob(self, value):
        v = _t(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - jax.scipy.special.gammaln(a))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _t(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        return Tensor(jax.random.dirichlet(_gen.next_key(), self.concentration,
                                           tuple(shape) + tuple(self._batch_shape)))

    def log_prob(self, value):
        v = _t(value)
        a = self.concentration
        norm = jnp.sum(jax.scipy.special.gammaln(a), -1) \
            - jax.scipy.special.gammaln(jnp.sum(a, -1))
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1) - norm)


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _t(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        u = jax.random.exponential(_gen.next_key(),
                                   tuple(shape) + tuple(self._batch_shape))
        return Tensor(u / self.rate)

    def log_prob(self, value):
        return Tensor(jnp.log(self.rate) - self.rate * _t(value))

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        z = jax.random.laplace(_gen.next_key(),
                               tuple(shape) + tuple(self._batch_shape))
        return Tensor(self.loc + self.scale * z)

    def log_prob(self, value):
        return Tensor(-jnp.abs(_t(value) - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale))


class Geometric(Distribution):
    def __init__(self, probs):
        self.probs_v = _t(probs)
        super().__init__(self.probs_v.shape)

    def sample(self, shape=()):
        u = jax.random.uniform(_gen.next_key(),
                               tuple(shape) + tuple(self._batch_shape))
        return Tensor(jnp.floor(jnp.log1p(-u) / jnp.log1p(-self.probs_v)))

    def log_prob(self, value):
        v = _t(value)
        return Tensor(v * jnp.log1p(-self.probs_v) + jnp.log(self.probs_v))


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        g = jax.random.gumbel(_gen.next_key(),
                              tuple(shape) + tuple(self._batch_shape))
        return Tensor(self.loc + self.scale * g)

    def log_prob(self, value):
        z = (_t(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(np.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        z = jax.random.normal(_gen.next_key(),
                              tuple(shape) + tuple(self._batch_shape))
        return Tensor(jnp.exp(self.loc + self.scale * z))

    def log_prob(self, value):
        v = _t(value)
        lv = jnp.log(v)
        var = self.scale ** 2
        return Tensor(-((lv - self.loc) ** 2) / (2 * var) - lv - jnp.log(self.scale)
                      - 0.5 * math.log(2 * math.pi))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_v = _t(probs)
        super().__init__(self.probs_v.shape[:-1], self.probs_v.shape[-1:])

    def sample(self, shape=()):
        logits = jnp.log(jnp.maximum(self.probs_v, 1e-30))
        draws = jax.random.categorical(
            _gen.next_key(), logits,
            shape=(self.total_count,) + tuple(shape) + tuple(self._batch_shape))
        k = self.probs_v.shape[-1]
        onehot = jax.nn.one_hot(draws, k)
        return Tensor(jnp.sum(onehot, axis=0))

    def log_prob(self, value):
        v = _t(value)
        logits = jnp.log(jnp.maximum(self.probs_v, 1e-30))
        return Tensor(jax.scipy.special.gammaln(jnp.sum(v, -1) + 1)
                      - jnp.sum(jax.scipy.special.gammaln(v + 1), -1)
                      + jnp.sum(v * logits, -1))


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        self.transforms = transforms if isinstance(transforms, (list, tuple)) else [transforms]
        super().__init__(base._batch_shape, base._event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        lp = jnp.zeros(())
        y = value
        for t in reversed(self.transforms):
            x = t.inverse(y)
            lp = lp - _t(t.forward_log_det_jacobian(x))
            y = x
        return Tensor(_t(self.base.log_prob(y)) + lp)


class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def forward(self, x):
        return Tensor(self.loc + self.scale * _t(x))

    def inverse(self, y):
        return Tensor((_t(y) - self.loc) / self.scale)

    def forward_log_det_jacobian(self, x):
        return Tensor(jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), _t(x).shape))


class ExpTransform(Transform):
    def forward(self, x):
        return Tensor(jnp.exp(_t(x)))

    def inverse(self, y):
        return Tensor(jnp.log(_t(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(_t(x))


class SigmoidTransform(Transform):
    def forward(self, x):
        return Tensor(jax.nn.sigmoid(_t(x)))

    def inverse(self, y):
        v = _t(y)
        return Tensor(jnp.log(v) - jnp.log1p(-v))

    def forward_log_det_jacobian(self, x):
        v = _t(x)
        return Tensor(-jax.nn.softplus(-v) - jax.nn.softplus(v))


def kl_divergence(p, q):
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        lp = jax.nn.log_softmax(p.logits, -1)
        lq = jax.nn.log_softmax(q.logits, -1)
        return Tensor(jnp.sum(jnp.exp(lp) * (lp - lq), -1))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        a = jnp.clip(p.probs_v, 1e-7, 1 - 1e-7)
        b = jnp.clip(q.probs_v, 1e-7, 1 - 1e-7)
        return Tensor(a * (jnp.log(a) - jnp.log(b))
                      + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))
    if isinstance(p, Exponential) and isinstance(q, Exponential):
        r = p.rate / q.rate
        return Tensor(jnp.log(r) + q.rate / p.rate - 1)
    # fallback: monte-carlo estimate
    x = p.sample((256,))
    return Tensor(jnp.mean(_t(p.log_prob(x)) - _t(q.log_prob(x)), axis=0))


# ---- breadth additions (ref distribution/cauchy.py, exponential_family.py,
# independent.py, kl.py register_kl) ----

class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _to_data(loc)
        self.scale = _to_data(scale)
        super().__init__(batch_shape=jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev")

    def sample(self, shape=()):
        k = _gen.next_key()
        shp = tuple(shape) + tuple(self.batch_shape)
        return Tensor(self.loc + self.scale * jax.random.cauchy(k, shp))

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        v = _to_data(value)
        z = (v - self.loc) / self.scale
        return Tensor(-jnp.log(jnp.pi) - jnp.log(self.scale) - jnp.log1p(z * z))

    def prob(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._data))

    def cdf(self, value):
        v = _to_data(value)
        return Tensor(jnp.arctan((v - self.loc) / self.scale) / jnp.pi + 0.5)

    def entropy(self):
        return Tensor(jnp.log(4 * jnp.pi) + jnp.log(self.scale)
                      + jnp.zeros(self.batch_shape))

    def kl_divergence(self, other):
        # closed form between two Cauchys (Chyzak-Nielsen 2019)
        t = ((self.scale + other.scale) ** 2 + (self.loc - other.loc) ** 2) / \
            (4 * self.scale * other.scale)
        return Tensor(jnp.log(t))


class ExponentialFamily(Distribution):
    """ref exponential_family.py: entropy via Bregman divergence of the
    log-normalizer.  Subclasses provide _natural_parameters/_log_normalizer."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0

    def entropy(self):
        nparams = [jnp.asarray(p) for p in self._natural_parameters]
        lg = self._log_normalizer(*[Tensor(p) for p in nparams])
        lg_data = lg._data if isinstance(lg, Tensor) else jnp.asarray(lg)
        result = lg_data - self._mean_carrier_measure
        # E[T(x)] . eta  via grad of log-normalizer
        g = jax.grad(lambda *ps: jnp.sum(
            (self._log_normalizer(*[Tensor(p) for p in ps])._data
             if isinstance(self._log_normalizer(*[Tensor(p) for p in ps]), Tensor)
             else self._log_normalizer(*ps))))(*nparams)
        gs = g if isinstance(g, (tuple, list)) else (g,)
        for p, gp in zip(nparams, gs):
            result = result - p * gp
        return Tensor(result)


class Independent(Distribution):
    """ref independent.py: reinterprets batch dims as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        bshape = tuple(getattr(base, "batch_shape", ()))
        k = self.reinterpreted_batch_rank
        super().__init__(batch_shape=bshape[:len(bshape) - k],
                         event_shape=bshape[len(bshape) - k:])

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        d = lp._data if isinstance(lp, Tensor) else jnp.asarray(lp)
        axes = tuple(range(d.ndim - self.reinterpreted_batch_rank, d.ndim))
        return Tensor(jnp.sum(d, axis=axes) if axes else d)

    def entropy(self):
        e = self.base.entropy()
        d = e._data if isinstance(e, Tensor) else jnp.asarray(e)
        axes = tuple(range(d.ndim - self.reinterpreted_batch_rank, d.ndim))
        return Tensor(jnp.sum(d, axis=axes) if axes else d)


_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """ref kl.py register_kl: decorator registering a KL(p||q) rule."""
    def decorator(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return decorator


_builtin_kl = kl_divergence


def kl_divergence(p, q):  # noqa: F811 — registry-aware front end
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            return fn(p, q)
    if hasattr(p, "kl_divergence") and type(p) is type(q):
        try:
            return p.kl_divergence(q)
        except (NotImplementedError, AttributeError):
            pass
    return _builtin_kl(p, q)
