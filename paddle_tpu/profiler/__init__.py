from .profiler import (Profiler, ProfilerState, ProfilerTarget, RecordEvent,  # noqa
                       SortedKeys, export_chrome_tracing, load_profiler_result,
                       make_scheduler)
from .timer import Benchmark, benchmark  # noqa
