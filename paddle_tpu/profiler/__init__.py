from .profiler import (Profiler, ProfilerState, ProfilerTarget, RecordEvent,  # noqa
                       SortedKeys, dump_chrome_trace, export_chrome_tracing,
                       is_recording, load_profiler_result, make_scheduler)
from .timer import Benchmark, benchmark  # noqa
