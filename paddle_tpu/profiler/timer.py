"""Throughput benchmark helper (reference: `python/paddle/profiler/timer.py:349` —
`Benchmark`, ips reporting with reader cost vs batch cost)."""
from __future__ import annotations

import time


class _Stat:
    def __init__(self):
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0
        self.last = 0.0

    def record(self, v):
        self.total += v
        self.count += 1
        self.last = v

    @property
    def avg(self):
        return self.total / self.count if self.count else 0.0


class Benchmark:
    def __init__(self):
        self.reader_cost = _Stat()
        self.batch_cost = _Stat()
        self._t_batch = None
        self._t_reader = None
        self.num_samples = 0

    def begin(self):
        self._t_batch = time.perf_counter()
        self._t_reader = self._t_batch

    def before_reader(self):
        self._t_reader = time.perf_counter()

    def after_reader(self):
        if self._t_reader is not None:
            self.reader_cost.record(time.perf_counter() - self._t_reader)

    def after_step(self, num_samples=1):
        now = time.perf_counter()
        if self._t_batch is not None:
            self.batch_cost.record(now - self._t_batch)
            self.num_samples += num_samples
        self._t_batch = now
        self._t_reader = now

    def step_info(self, unit="samples"):
        ips = (1.0 / self.batch_cost.avg) if self.batch_cost.avg else 0.0
        return (f"reader_cost: {self.reader_cost.avg:.5f} s, batch_cost: "
                f"{self.batch_cost.avg:.5f} s, ips: {ips:.2f} {unit}/s")


_bench = Benchmark()


def benchmark():
    return _bench
